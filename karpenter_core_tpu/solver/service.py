"""solverd: the TPU solver as a supervised sidecar process.

SURVEY §7 / BASELINE frame the paper's architecture as Go reconcilers
feeding pod×InstanceType tensor problems to a TPU solver across a process
boundary; this server IS that boundary's solver side, promoted from the
codec-only seam (solver/codec.py called itself "the solver's process
boundary" while nothing served it). It speaks HTTP+npz instead of
gRPC+proto — same split, stdlib transport (the kube/httpserver.py pattern):

* ``POST /solve``        — full scheduler input -> DeviceScheduler.solve
                           (schedulers cached per problem fingerprint, so
                           repeat solves against an unchanged cluster reuse
                           the prepared-state caches across RPC calls)
* ``POST /consolidate``  — consolidation prefix sweep (frontier_core)
* ``GET  /healthz``      — liveness + readiness + admission-queue depth
                           (``ready: false`` while the queue is saturated,
                           so probes tell "overloaded" from "dead")
* ``GET  /metrics``      — the sidecar's own registry, exposition format
* ``POST /profile``      — toggle jax.profiler trace capture around solves
                           (requires ``--profile-dir``); GET reports state

Since the fleet gateway (solver/fleet.py) landed, one sidecar serves N
operators: every request carries a tenant (wire field + ``X-Solver-Tenant``
header) and a remaining deadline (``X-Solver-Deadline``), admission sheds
hopeless requests with ``429 + Retry-After`` (the client degrades that
solve to its host greedy path), tenants share the device under weighted
fair queueing with provisioning prioritized over consolidation sweeps, and
only the device phase of a request is exclusive — request B's codec
decode/encode overlaps request A's device time.

Responses carry ``X-Solver-Seconds`` (device solve wall time) so the client
can split its RPC histogram into transit vs kernel. Boot enables the
persistent XLA compile cache and optionally pre-warms the common class-count
shape buckets (the bench restart-probe path), turning the first-batch
compile cliff into a cache load.

Run: ``python -m karpenter_core_tpu.solver.service --port 0``
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from karpenter_core_tpu.kube.httpserver import read_body, send_body
from karpenter_core_tpu.solver import codec, fleet

_OCTET = "application/octet-stream"


class SolverDaemon:
    """Request execution, transport-free (tests drive it directly).

    Schedulers are cached per problem fingerprint (everything in the solve
    request EXCEPT the pending pods and the tenant — see
    codec.problem_fingerprint): a control plane re-solving against an
    unchanged cluster reuses the same DeviceScheduler across RPC calls,
    which carries the prepared-state caches (vocab-keyed catalog tensors,
    per-class rows, device-resident class steps) across the wire boundary.
    Any change to the problem half changes the fingerprint and builds a
    fresh scheduler, so cached and uncached solves are packing-identical
    by construction (conformance battery in tests/test_solverd.py). The
    cache is LRU-bounded in entries AND approximate bytes
    (fleet.BoundedSchedulerCache) so a fleet of heterogeneous tenants
    cannot OOM the sidecar.

    The fleet gateway sequences the device: a request holds exclusivity
    only between ``await_grant`` and ``release`` — its codec decode runs
    before the grant and its result encode after the release, both on the
    request's own handler thread, so host work pipelines under the device
    phase of whichever request currently owns the chip. A cached
    DeviceScheduler is not reentrant; the single-grant gateway is what
    makes that safe."""

    def __init__(
        self,
        profile_dir: str = None,
        gateway: fleet.FleetGateway = None,
        sched_cache: fleet.BoundedSchedulerCache = None,
        devices: int = 1,
    ):
        self.ready = False
        self.solves = 0
        self.profile_dir = profile_dir
        # shard every solve/sweep over the first N local devices (0 = all;
        # requests clamp to what exists, so a multi-device config degrades
        # to the single-device path on a 1-chip box). Resolved lazily per
        # scheduler construction — the daemon must stay importable without
        # initializing the XLA backend.
        self.devices = devices
        self.profiling = False
        self.gateway = gateway if gateway is not None else fleet.FleetGateway()
        # `is None`, not truthiness: an EMPTY BoundedSchedulerCache is
        # falsy (len 0) but must still be adopted, or the caller's bounds
        # would silently be replaced with the defaults
        self._sched_cache = (
            sched_cache
            if sched_cache is not None
            else fleet.BoundedSchedulerCache()
        )
        self._state_lock = threading.Lock()

    # -- endpoints ---------------------------------------------------------

    def solve(self, body: bytes, tenant: str = None, deadline: float = None):
        """bytes -> (response bytes, solve seconds). Raises fleet.ShedError
        when admission rejects the request (the HTTP layer answers 429 +
        Retry-After; solver/remote.py degrades that solve to greedy).

        ``tenant`` is the transport-level identity (the X-Solver-Tenant
        header) and wins when present; a direct-drive caller that passes
        none is accounted to the tenant on the wire."""
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.models.provisioner import DeviceScheduler

        ticket = self.gateway.submit(
            tenant or fleet.DEFAULT_TENANT, fleet.LANE_SOLVE, deadline
        )
        try:
            # host phase: decode runs on this handler thread with the
            # device NOT held — request B decodes under request A's kernel
            problem = self._decode_solve(body)
            if tenant is None:
                ticket.tenant = problem["tenant"]
        except BaseException:
            self.gateway.abandon(ticket)
            raise
        self.gateway.await_grant(ticket)  # may raise ShedError (expired)
        dt = 0.0
        grant_t0 = time.perf_counter()
        try:
            # device phase: the only exclusive section
            scheduler = self._sched_cache.get(problem["fingerprint"])
            if scheduler is None:
                m.SOLVERD_SCHED_CACHE.inc({"outcome": "miss"})
                scheduler = DeviceScheduler(
                    problem["nodepools"],
                    problem["instance_types"],
                    existing_nodes=problem["existing_nodes"],
                    daemonset_pods=problem["daemonset_pods"],
                    max_slots=problem["max_slots"],
                    topology=problem["topology"],
                    unavailable_offerings=problem["unavailable_offerings"],
                    devices=self.devices,
                )
                # the encoded request size is the entry's weight proxy: it
                # tracks catalog/node scale without walking device buffers
                self._sched_cache.put(
                    problem["fingerprint"], scheduler, len(body)
                )
            else:
                m.SOLVERD_SCHED_CACHE.inc({"outcome": "hit"})
                # the fingerprint ignores the pod-derived excluded-uid
                # list; hand the cached scheduler this request's live
                # topology context so exclusions are never stale
                scheduler.update_topology_context(problem["topology"])
            t0 = time.perf_counter()
            with self._maybe_profile():
                results = scheduler.solve(problem["pods"])
            dt = time.perf_counter() - t0
            # handler threads run concurrently; a bare += is a lost update
            with self._state_lock:
                self.solves += 1
        finally:
            # charge the FULL exclusive occupancy — cache-miss scheduler
            # construction/prepare included, and the elapsed time even
            # when the solve raised. Fairness and the admission p50 must
            # see what the device actually lost; charging only the kernel
            # would let cache-churning tenants under-pay and a raising
            # solve would drag the p50 estimator toward zero. The kernel
            # time alone (dt) still rides X-Solver-Seconds so the client's
            # transit/kernel histogram split stays honest.
            self.gateway.release(ticket, time.perf_counter() - grant_t0)
        m.SOLVERD_TENANT_SOLVES.inc(
            {"tenant": ticket.tenant, "endpoint": "solve"}
        )
        # host phase again: encode outside the grant, the next tenant's
        # device phase is already running
        return codec.encode_solve_results(results, dt), dt

    def _decode_solve(self, body: bytes) -> dict:
        """The solve request's host-phase decode — a named seam so chaos
        tests can wedge ONE tenant's host phase and prove the device keeps
        serving everyone else."""
        return codec.decode_solve_request(body)

    def _maybe_profile(self):
        """jax.profiler trace context when profiling is toggled on and a
        --profile-dir was configured; a no-op context otherwise. Lets TPU
        traces be captured from a RUNNING sidecar (POST /profile) without
        a redeploy."""
        import contextlib

        if self.profiling and self.profile_dir:
            import jax.profiler

            return jax.profiler.trace(self.profile_dir)
        return contextlib.nullcontext()

    def toggle_profile(self, enable: bool = None) -> dict:
        # read-modify-write (enable=None flips the current state) under its
        # own small lock: two concurrent POST /profile toggles must not both
        # read the same old value. Deliberately NOT a gateway ticket — a
        # toggle must not queue behind a multi-second solve.
        with self._state_lock:
            if enable is None:
                enable = not self.profiling
            self.profiling = bool(enable) and self.profile_dir is not None
            return {
                "profiling": self.profiling,
                "profile_dir": self.profile_dir,
                "configured": self.profile_dir is not None,
            }

    def consolidate(
        self, body: bytes, tenant: str = None, deadline: float = None
    ):
        """Consolidation sweeps ride the gateway's NORMAL lane: under
        contention every pending provisioning solve dispatches first."""
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.models.consolidation import frontier_core

        ticket = self.gateway.submit(
            tenant or fleet.DEFAULT_TENANT, fleet.LANE_SWEEP, deadline
        )
        try:
            req = codec.decode_frontier_request(body)
            if tenant is None:
                ticket.tenant = req["tenant"]
        except BaseException:
            self.gateway.abandon(ticket)
            raise
        self.gateway.await_grant(ticket)
        dt = 0.0
        grant_t0 = time.perf_counter()
        try:
            t0 = time.perf_counter()
            frontier = frontier_core(
                req["nodepools"],
                req["instance_types"],
                req["cand_nodes"],
                req["keep_nodes"],
                req["daemonset_pods"],
                req["base_pods"],
                req["candidate_pods"],
                max_slots=req["max_slots"],
                devices=self.devices,
            )
            dt = time.perf_counter() - t0
        finally:
            # full-occupancy charge, as in solve()
            self.gateway.release(ticket, time.perf_counter() - grant_t0)
        m.SOLVERD_TENANT_SOLVES.inc(
            {"tenant": ticket.tenant, "endpoint": "consolidate"}
        )
        return codec.encode_frontier_response(frontier), dt

    def health(self) -> dict:
        """The /healthz body: liveness (warm-up finished) + readiness
        (liveness AND the admission queue below its bound). An overloaded
        sidecar is alive-but-unready — the supervisor must not respawn it
        into a load spike (a restart storm turns overload into outage)."""
        depth = self.gateway.depth()
        saturated = self.gateway.saturated()
        return {
            "ok": self.ready,
            "ready": bool(self.ready and not saturated),
            "overloaded": saturated,
            "queue_depth": depth,
            "queue_capacity": self.gateway.max_depth,
        }

    # -- boot warm-up ------------------------------------------------------

    def warm_up(self, prewarm: bool = False) -> None:
        """Compile-cache bootstrap: always point XLA's persistent cache at
        the repo-local directory; with ``prewarm`` also run the synthetic
        shape-bucket solves so a restarted sidecar serves its first real
        batch from the jit cache instead of a compile cliff."""
        from karpenter_core_tpu.utils.jaxenv import (
            enable_persistent_compile_cache,
        )

        enable_persistent_compile_cache()
        if prewarm:
            from karpenter_core_tpu.api.nodepool import NodePool, NodePoolSpec
            from karpenter_core_tpu.api.objects import ObjectMeta
            from karpenter_core_tpu.cloudprovider.kwok import build_catalog
            from karpenter_core_tpu.models.provisioner import DeviceScheduler

            pool = NodePool(metadata=ObjectMeta(name="prewarm"))
            pool.spec = NodePoolSpec()
            catalog = build_catalog(cpu_grid=[1, 2, 4, 8], mem_factors=[2, 4])
            DeviceScheduler(
                [pool], {"prewarm": catalog}, max_slots=256,
                devices=self.devices,
            ).prewarm()
        self.ready = True


class _Handler(BaseHTTPRequestHandler):
    server_version = "karpenter-solverd/1"
    daemon: SolverDaemon

    def log_message(self, *args) -> None:  # quiet
        pass

    def do_GET(self) -> None:
        path = self.path.split("?")[0]
        if path == "/healthz":
            health = self.daemon.health()
            send_body(
                self,
                200 if health["ok"] else 503,
                json.dumps(health).encode(),
            )
        elif path == "/metrics":
            from karpenter_core_tpu.metrics.registry import REGISTRY

            send_body(
                self, 200, REGISTRY.render().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/profile":
            send_body(
                self, 200,
                json.dumps(self.daemon.toggle_profile(
                    self.daemon.profiling  # GET reports, never toggles
                )).encode(),
            )
        else:
            send_body(self, 404, b'{"error": "not found"}')

    def _request_identity(self):
        """(tenant, deadline) from transport headers. The header is the
        gateway's pre-decode identity; the wire's tenant field backs it up
        for header-less clients. A malformed deadline means no deadline
        (shedding on garbage would turn a client bug into an outage)."""
        tenant = self.headers.get("X-Solver-Tenant") or None
        deadline = None
        raw = self.headers.get("X-Solver-Deadline")
        if raw:
            try:
                deadline = float(raw)
            except ValueError:
                deadline = None
        if deadline is not None and deadline <= 0:
            deadline = None
        return tenant, deadline

    def do_POST(self) -> None:
        path, _, query = self.path.partition("?")
        body = read_body(self)
        tenant, deadline = self._request_identity()
        try:
            if path == "/solve":
                out, dt = self.daemon.solve(
                    body, tenant=tenant, deadline=deadline
                )
            elif path == "/consolidate":
                out, dt = self.daemon.consolidate(
                    body, tenant=tenant, deadline=deadline
                )
            elif path == "/profile":
                from urllib.parse import parse_qs

                q = parse_qs(query)
                enable = None
                if "enable" in q:
                    enable = q["enable"][0] not in ("0", "false", "off")
                state = self.daemon.toggle_profile(enable)
                return send_body(self, 200, json.dumps(state).encode())
            else:
                return send_body(self, 404, b'{"error": "not found"}')
        except fleet.ShedError as e:
            # overload is a CONTRACT, not an error: 429 + the gateway's
            # retry estimate; the client degrades this solve to greedy
            return send_body(
                self, 429,
                json.dumps(
                    {"error": "overloaded", "reason": e.reason}
                ).encode(),
                headers={"Retry-After": f"{e.retry_after:.3f}"},
            )
        except Exception as e:
            return send_body(
                self, 500, repr(e).encode(), ctype="text/plain"
            )
        send_body(
            self, 200, out, _OCTET, headers={"X-Solver-Seconds": f"{dt:.6f}"}
        )


def serve(
    port: int,
    host: str = "127.0.0.1",
    daemon: SolverDaemon = None,
    ready: bool = True,
) -> ThreadingHTTPServer:
    """Serve solverd on host:port in a daemon thread; returns the server
    (port 0 picks a free one — server_address[1]). ``ready=True`` marks the
    daemon ready immediately (in-thread test servers skip warm-up)."""
    d = daemon or SolverDaemon()
    if ready:
        d.ready = True
    handler = type("BoundSolverd", (_Handler,), {"daemon": d})
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.daemon_ = d
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description="karpenter TPU solver sidecar")
    ap.add_argument("--port", type=int, default=8181)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument(
        "--prewarm", action="store_true",
        help="compile the common shape buckets before serving traffic",
    )
    ap.add_argument(
        "--profile-dir", default=None,
        help="directory for jax.profiler traces; solves are wrapped in a"
        " trace capture while profiling is toggled on via POST /profile"
        " (off by default), so TPU-side traces can be grabbed from a"
        " running sidecar without redeploying",
    )
    ap.add_argument(
        "--queue-depth", type=int, default=fleet.DEFAULT_QUEUE_DEPTH,
        help="admission bound: requests in flight (queued + host phase +"
        " device) before the gateway sheds with 429 + Retry-After",
    )
    ap.add_argument(
        "--tenant-weights", default="",
        help="fair-share weights as 'tenant=weight,...' (default weight 1:"
        " a weight-3 tenant gets ~3x the device share under contention)",
    )
    ap.add_argument(
        "--cache-entries", type=int, default=fleet.DEFAULT_CACHE_ENTRIES,
        help="DeviceScheduler cache entry bound (one entry per distinct"
        " problem fingerprint across all tenants)",
    )
    ap.add_argument(
        "--cache-mib", type=int,
        default=fleet.DEFAULT_CACHE_BYTES >> 20,
        help="DeviceScheduler cache approximate-byte bound, in MiB"
        " (encoded-request-size proxy per entry)",
    )
    ap.add_argument(
        "--devices", type=int, default=1,
        help="shard every solve/sweep over the first N local devices"
        " (pjit over the slot axis; 0 = all local devices, 1 ="
        " single-device). Requests clamp to what exists, so a slice"
        " config degrades to single-device on a 1-chip box",
    )
    args = ap.parse_args()
    if args.devices < 0:
        ap.error("--devices must be >= 0 (0 = all local devices)")

    daemon = SolverDaemon(
        profile_dir=args.profile_dir,
        gateway=fleet.FleetGateway(
            max_depth=args.queue_depth,
            weights=fleet.parse_tenant_weights(args.tenant_weights),
        ),
        sched_cache=fleet.BoundedSchedulerCache(
            max_entries=args.cache_entries,
            max_bytes=args.cache_mib << 20,
        ),
        devices=args.devices,
    )
    httpd = serve(args.port, host=args.host, daemon=daemon, ready=False)
    # the supervisor (solver/supervisor.py) reads this line to learn the
    # bound address — same handshake as kube/httpserver.py
    print(
        f"listening on {httpd.server_address[0]}:{httpd.server_address[1]}",
        flush=True,
    )
    daemon.warm_up(prewarm=args.prewarm)
    print("ready", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        httpd.server_close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
