"""solverd: the TPU solver as a supervised sidecar process.

SURVEY §7 / BASELINE frame the paper's architecture as Go reconcilers
feeding pod×InstanceType tensor problems to a TPU solver across a process
boundary; this server IS that boundary's solver side, promoted from the
codec-only seam (solver/codec.py called itself "the solver's process
boundary" while nothing served it). It speaks HTTP+npz instead of
gRPC+proto — same split, stdlib transport (the kube/httpserver.py pattern):

* ``POST /solve``        — full scheduler input -> DeviceScheduler.solve
                           (schedulers cached per problem fingerprint, so
                           repeat solves against an unchanged cluster reuse
                           the prepared-state caches across RPC calls)
* ``POST /consolidate``  — consolidation prefix sweep (frontier_core)
* ``GET  /healthz``      — liveness + readiness + admission-queue depth
                           (``ready: false`` while the queue is saturated,
                           so probes tell "overloaded" from "dead")
* ``GET  /metrics``      — the sidecar's own registry, exposition format
* ``POST /profile``      — toggle jax.profiler trace capture around solves
                           (requires ``--profile-dir``); GET reports state
* ``POST /drain``        — crash-only clean restart: admission closes,
                           queued requests answer 503 (drain ≠ shed ≠
                           fault), and the process exits with
                           DRAIN_EXIT_CODE once the in-flight device step
                           clears — the supervisor respawns immediately
                           without charging crash-loop backoff

Two survivability guards wrap the exclusive device step: a ``DeviceWatchdog``
(hard wall-clock bound; on overrun the queue is flushed with 503s and the
process exits crash-only with WATCHDOG_EXIT_CODE — Python cannot kill a
wedged device thread, so the process IS the unit of recovery) and a
``PoisonQuarantine`` (a request-body digest that crashes/wedges the device
N times inside a TTL is refused pre-decode with 422, so one tenant's
poison problem cannot crash-loop the shared sidecar for the whole fleet;
an optional journal carries the in-flight digest across the very crash it
causes).

Since the fleet gateway (solver/fleet.py) landed, one sidecar serves N
operators: every request carries a tenant (wire field + ``X-Solver-Tenant``
header) and a remaining deadline (``X-Solver-Deadline``), admission sheds
hopeless requests with ``429 + Retry-After`` (the client degrades that
solve to its host greedy path), tenants share the device under weighted
fair queueing with provisioning prioritized over consolidation sweeps, and
only the device phase of a request is exclusive — request B's codec
decode/encode overlaps request A's device time.

With continuous batching on (``--max-batch`` > 1), a granted solve also
COALESCES: it collects compatible queued problems (same compile-shape
bucket via ``codec.problem_bucket``, distinct fingerprints) and solves
them all in one vmapped multi-problem device dispatch
(models/provisioner.solve_batch) under its single grant — many small
tenant solves amortize one device window instead of serializing, while
each problem's decode/verify/encode stays per-request on its own handler
thread and a poisoned batch member fails alone.

Responses carry ``X-Solver-Seconds`` (device solve wall time) so the client
can split its RPC histogram into transit vs kernel. Boot enables the
persistent XLA compile cache and optionally pre-warms the common class-count
shape buckets (the bench restart-probe path), turning the first-batch
compile cliff into a cache load.

Run: ``python -m karpenter_core_tpu.solver.service --port 0``
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from karpenter_core_tpu.kube.httpserver import read_body, send_body
from karpenter_core_tpu.solver import codec, fleet, segments
from karpenter_core_tpu.solver import incremental as incsolve
from karpenter_core_tpu.solver.autoscale import BROWNOUT_MAX_RUNG
from karpenter_core_tpu.solver.supervisor import (
    DRAIN_EXIT_CODE,
    DRAIN_EXIT_DEADLINE_SECONDS,
    WATCHDOG_EXIT_CODE,
)

_OCTET = "application/octet-stream"

# brownout ladder shape (ISSUE 17): rung 2 widens the coalescing window
# by WINDOW_FACTOR (with a floor so a zero-window gateway still widens),
# rung 3 scales admission capacity by SHED_FACTOR so shedding starts
# earlier. Rung 1 costs nothing here — it only rewrites relax -> ffd in
# solve(). Verification is NEVER touched by any rung.
BROWNOUT_WINDOW_FACTOR = 4.0
BROWNOUT_WINDOW_FLOOR = 0.01
BROWNOUT_SHED_FACTOR = 0.5

# grace window between flushing the queue (503s written by their handler
# threads) and the crash-only process exit — long enough for in-memory
# socket writes, short enough that a wedged chip is gone in well under a
# supervision pass
_EXIT_GRACE_SECONDS = 0.25


class DeviceWatchdog:
    """Hard wall-clock bound on the EXCLUSIVE device step.

    A wedged device solve (driver hang, pathological compile, poisoned
    input) holds the single device grant forever: every tenant's solves
    queue behind it until their deadlines shed, and the whole fleet
    silently degrades to greedy. Python cannot kill the wedged thread, so
    the recovery is crash-only: on trip the daemon drains the gateway
    (queued requests answer 503 instead of vanishing), the process exits
    with WATCHDOG_EXIT_CODE, and the supervisor respawns it — the
    quarantine journal remembers the fingerprint that wedged it.

    Armed/disarmed around each device phase; the monitor thread wakes a
    few times a second and only ever reads two floats, so the idle cost is
    noise. ``check()`` evaluates once synchronously (the deterministic
    test hook)."""

    def __init__(
        self,
        budget_seconds: float,
        on_trip,
        exit_fn=None,
        time_fn=time.monotonic,
        poll_seconds: float = 0.05,
    ):
        if budget_seconds <= 0:
            raise ValueError(
                f"watchdog budget must be positive, got {budget_seconds}"
            )
        self.budget_seconds = budget_seconds
        self.on_trip = on_trip
        # None = report-and-drain only (in-thread test servers must not
        # take the test process down with them); solverd main passes
        # os._exit for the real crash-only contract
        self.exit_fn = exit_fn
        self.time_fn = time_fn
        self.poll_seconds = poll_seconds
        self.trips = 0
        self._lock = threading.Lock()
        self._armed_at = None
        self._note = ""
        self._thread = None

    def arm(self, note: str = "") -> None:
        with self._lock:
            self._armed_at = self.time_fn()
            self._note = note
            # poll_seconds == 0 runs without a monitor thread — the
            # deterministic mode where tests drive check() themselves
            if self._thread is None and self.poll_seconds > 0:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="solverd-watchdog",
                )
                self._thread.start()

    def disarm(self) -> None:
        with self._lock:
            self._armed_at = None
            self._note = ""

    def armed(self) -> bool:
        with self._lock:
            return self._armed_at is not None

    def _loop(self) -> None:
        while True:
            time.sleep(self.poll_seconds)
            self.check()

    def check(self) -> bool:
        """One evaluation: trip when the armed device step has overrun its
        budget. Returns True when it tripped."""
        with self._lock:
            armed_at, note = self._armed_at, self._note
        if armed_at is None:
            return False
        if self.time_fn() - armed_at < self.budget_seconds:
            return False
        return self._trip(armed_at, note)

    def _trip(self, armed_at: float, note: str) -> bool:
        from karpenter_core_tpu.metrics import wiring as m

        with self._lock:
            # re-validate under the lock: the step may have finished
            # (disarm) — or a NEW step armed — between the monitor's read
            # and now; tripping on a stale observation would kill a
            # healthy sidecar and charge the supervisor's crash backoff
            if self._armed_at != armed_at:
                return False
            self._armed_at = None  # never double-trip on one overrun
            self._note = ""
            self.trips += 1
        m.SOLVERD_WATCHDOG_TRIPS.inc()
        try:
            self.on_trip(note)
        finally:
            if self.exit_fn is not None:
                time.sleep(_EXIT_GRACE_SECONDS)
                self.exit_fn(WATCHDOG_EXIT_CODE)
        return True


class SolverDaemon:
    """Request execution, transport-free (tests drive it directly).

    Schedulers are cached per problem fingerprint (everything in the solve
    request EXCEPT the pending pods and the tenant — see
    codec.problem_fingerprint): a control plane re-solving against an
    unchanged cluster reuses the same DeviceScheduler across RPC calls,
    which carries the prepared-state caches (vocab-keyed catalog tensors,
    per-class rows, device-resident class steps) across the wire boundary.
    Any change to the problem half changes the fingerprint and builds a
    fresh scheduler, so cached and uncached solves are packing-identical
    by construction (conformance battery in tests/test_solverd.py). The
    cache is LRU-bounded in entries AND approximate bytes
    (fleet.BoundedSchedulerCache) so a fleet of heterogeneous tenants
    cannot OOM the sidecar.

    The fleet gateway sequences the device: a request holds exclusivity
    only between ``await_grant`` and ``release`` — its codec decode runs
    before the grant and its result encode after the release, both on the
    request's own handler thread, so host work pipelines under the device
    phase of whichever request currently owns the chip. A cached
    DeviceScheduler is not reentrant; the single-grant gateway is what
    makes that safe."""

    def __init__(
        self,
        profile_dir: str = None,
        gateway: fleet.FleetGateway = None,
        sched_cache: fleet.BoundedSchedulerCache = None,
        devices: int = 1,
        watchdog_seconds: float = 0.0,
        quarantine: fleet.PoisonQuarantine = None,
        chaos=None,
        exit_fn=None,
        default_mode: str = "ffd",
        kernel: str = "xla",
        segment_store: segments.SegmentStore = None,
        incremental=None,
    ):
        self.ready = False
        self.solves = 0
        self.profile_dir = profile_dir
        # boot identity for the delta wire (segmentstore, ISSUE 14): rides
        # every answer as X-Solverd-Instance and every segment-miss 409,
        # so clients key their sent-caches per PROCESS — a respawn mints
        # a fresh id and costs exactly one re-upload round, never a stale
        # elision against an empty store
        import uuid

        self.instance = uuid.uuid4().hex[:12]
        # content-addressed segment store: what a manifest request's
        # digests resolve against (`is None`, not truthiness — an empty
        # store must still be adopted, the PR 5 cache lesson)
        self.segment_store = (
            segment_store
            if segment_store is not None
            else segments.SegmentStore()
        )
        # incremental re-solve engine (incsolve, ISSUE 16): entered only
        # when a request names its predecessor (prev_fingerprint on the
        # wire), so non-incremental clients never change behavior. The
        # ledger is process-local like the scheduler cache — a respawned
        # member's empty ledger degrades to a full solve (amnesia), and
        # the fleet router's digest affinity keeps a snapshot's requests
        # on the member whose ledger is warm. ``False`` disables; None
        # builds the default engine; an engine instance is adopted
        # (`is None` would wrongly re-enable an explicit False).
        if incremental is False:
            self.incremental = None
        elif incremental is None:
            self.incremental = incsolve.IncrementalEngine()
        else:
            self.incremental = incremental
        # solver backend served when a request names none (relaxsolve,
        # ISSUE 13): the wire field / X-Solver-Mode header select
        # per-request; this is the daemon-wide default (solverd
        # --solver-mode, riding the supervisor spawn argv)
        if default_mode not in codec.SOLVER_MODES:
            raise ValueError(f"unknown solver mode {default_mode!r}")
        self.default_mode = default_mode
        # which kernel implementation answers the FFD scan dispatches
        # (ISSUE 18, solverd --kernel riding the supervisor spawn argv):
        # xla = classic per-op lowering, pallas = the hand-fused per-class
        # kernel (ops/pallas_ffd.py). Daemon-wide — results are
        # byte-identical either way, so unlike solver mode it needs no
        # per-request wire field; it still suffixes the coalescer bucket
        # (below) so a mixed-kernel fleet's members never share a
        # problem_bucket string with different device programs behind it.
        if kernel not in ("xla", "pallas"):
            raise ValueError(f"unknown kernel {kernel!r} (xla | pallas)")
        self.kernel = kernel
        # shard every solve/sweep over the first N local devices (0 = all;
        # requests clamp to what exists, so a multi-device config degrades
        # to the single-device path on a 1-chip box). Resolved lazily per
        # scheduler construction — the daemon must stay importable without
        # initializing the XLA backend.
        self.devices = devices
        self.profiling = False
        self.gateway = gateway if gateway is not None else fleet.FleetGateway()
        # `is None`, not truthiness: an EMPTY BoundedSchedulerCache is
        # falsy (len 0) but must still be adopted, or the caller's bounds
        # would silently be replaced with the defaults
        self._sched_cache = (
            sched_cache
            if sched_cache is not None
            else fleet.BoundedSchedulerCache()
        )
        self._state_lock = threading.Lock()
        # brownout ladder state (ISSUE 17): the current rung (0 = clear)
        # and the gateway shape captured at first rung entry, restored on
        # descent. The rung itself is read un-locked on the solve path
        # (an atomic int read; a one-request-late rung switch is fine).
        self.brownout_rung = 0
        self._brownout_base = None
        # poison-pill quarantine: a request whose body digest has crashed
        # the device step N times is refused pre-decode (HTTP 422), so one
        # tenant's poison cannot re-wedge the shared sidecar for everyone
        self.quarantine = (
            quarantine
            if quarantine is not None
            else fleet.PoisonQuarantine(site="gateway")
        )
        # chaos injector (chaos.SolverChaos): wedge/corrupt-wire/bad-result
        # faults on the device tier, None in production
        self.chaos = chaos
        # None = exit disabled (in-thread test servers); solverd main
        # passes os._exit so drain/watchdog exits are truly crash-only
        self.exit_fn = exit_fn
        self.watchdog = (
            DeviceWatchdog(
                watchdog_seconds, on_trip=self._on_watchdog_trip,
                exit_fn=exit_fn,
            )
            if watchdog_seconds > 0
            else None
        )

    def _on_watchdog_trip(self, note: str) -> None:
        """Crash-only exit path: queued requests answer 503 (drain flush)
        instead of vanishing into the process exit; the wedged thread keeps
        the device — only the exit reclaims it."""
        self.gateway.drain()

    def drain(self) -> dict:
        """POST /drain: stop admission, flush the queue (each queued
        request's handler answers 503), then — when an exit_fn is wired —
        exit with DRAIN_EXIT_CODE once the in-flight device step clears,
        so the supervisor respawns a clean process without charging
        crash-loop backoff."""
        flushed = self.gateway.drain()
        if self.exit_fn is not None:
            t = threading.Thread(
                target=self._exit_after_idle, daemon=True,
                name="solverd-drain-exit",
            )
            t.start()
        return {
            "draining": True,
            "flushed": flushed,
            "exiting": self.exit_fn is not None,
        }

    def _exit_after_idle(self) -> None:
        """Wait (bounded) for the active device step to finish, then exit
        cleanly. A step that outlives the wait is wedged — the drain exit
        proceeds anyway; crash-only beats hanging the restart."""
        deadline = time.monotonic() + DRAIN_EXIT_DEADLINE_SECONDS
        while time.monotonic() < deadline and self.gateway.depth() > 0:
            time.sleep(0.05)
        time.sleep(_EXIT_GRACE_SECONDS)
        self.exit_fn(DRAIN_EXIT_CODE)

    def set_brownout(self, rung: int) -> dict:
        """POST /brownout: enter/exit one rung of the explicit degradation
        ladder (the autoscaler owns the hysteresis; this applies effects).
        Rung 1: relax requests are served in FFD mode (the anytime answer
        — solve() rewrites the effective mode, verifier untouched).
        Rung 2: the batch window widens for deeper coalescing. Rung 3:
        admission capacity halves so shedding starts earlier. Descent
        restores the captured gateway shape; every rung is visible on
        /healthz and the solverd_brownout_rung gauge."""
        from karpenter_core_tpu.metrics import wiring as m

        if not 0 <= int(rung) <= BROWNOUT_MAX_RUNG:
            raise ValueError(
                f"brownout rung must be in [0, {BROWNOUT_MAX_RUNG}],"
                f" got {rung!r}"
            )
        rung = int(rung)
        with self._state_lock:
            previous = self.brownout_rung
            if self._brownout_base is None:
                self._brownout_base = (
                    self.gateway.batch_window, self.gateway.max_depth
                )
            base_window, base_depth = self._brownout_base
            self.brownout_rung = rung
        # gateway retunes take the GATEWAY lock — applied after the
        # daemon state lock is released, never nested under it
        if rung >= 2 and self.gateway.max_batch > 1:
            window = max(
                base_window * BROWNOUT_WINDOW_FACTOR, BROWNOUT_WINDOW_FLOOR
            )
        else:
            window = base_window
        self.gateway.set_batch_window(window)
        depth = (
            max(int(base_depth * BROWNOUT_SHED_FACTOR), 1)
            if rung >= 3 else base_depth
        )
        self.gateway.set_max_depth(depth)
        m.SOLVERD_BROWNOUT_RUNG.set(float(rung))
        return {
            "rung": rung,
            "previous": previous,
            "batch_window_s": window,
            "queue_capacity": depth,
        }

    # -- endpoints ---------------------------------------------------------

    def solve(self, body: bytes, tenant: str = None, deadline: float = None,
              solver_mode: str = None):
        """bytes -> (response bytes, solve seconds). Raises fleet.ShedError
        when admission rejects the request (the HTTP layer answers 429 +
        Retry-After; solver/remote.py degrades that solve to greedy),
        fleet.DrainError while draining (503), and fleet.QuarantinedError
        for a poison-pill digest (422) — all BEFORE any decode or device
        work, so refusals cost the sidecar nothing.

        ``tenant`` is the transport-level identity (the X-Solver-Tenant
        header) and wins when present; a direct-drive caller that passes
        none is accounted to the tenant on the wire.

        With batching enabled (gateway max_batch > 1), a granted request
        becomes the batch LEADER: it collects compatible queued problems
        (same shape bucket, distinct fingerprints) and solves them all
        under its one device grant as a vmapped multi-problem batch
        (models/provisioner.solve_batch). Collected members wake with
        state="batched", wait for their ISOLATED per-problem outcome, and
        encode their own responses on their own handler threads — so the
        per-problem decode/verify/encode fan-out stays in the host phases
        and one corrupt or poisoned problem in a batch fails alone."""
        from karpenter_core_tpu.metrics import wiring as m

        # the poison key is the request digest (canonical wire bytes for
        # full bodies, the manifest CORE for delta bodies — the same key
        # whether or not segment uploads ride along), computed pre-decode:
        # the decode itself may be the crash. For a manifest this parses
        # the (small) header and resolves the listing a second time
        # alongside _decode_solve — accepted: the heavy JSON (segment
        # contents) is only ever parsed once, in assembly, and both
        # passes run in the pipelined host phase, never on the grant.
        digest = codec.request_digest(
            body, segment_store=self.segment_store
        )
        if self.quarantine.quarantined(digest):
            m.SOLVER_QUARANTINE_ROUTED.inc({"site": "gateway"})
            raise fleet.QuarantinedError(digest)
        ticket = self.gateway.submit(
            tenant or fleet.DEFAULT_TENANT, fleet.LANE_SOLVE, deadline
        )
        try:
            # host phase: decode runs on this handler thread with the
            # device NOT held — request B decodes under request A's kernel
            problem = self._decode_solve(body)
            if tenant is None:
                ticket.tenant = problem["tenant"]
            # solver-mode resolution (relaxsolve, ISSUE 13): transport
            # header > wire field > daemon default. A resolved mode that
            # differs from the wire's suffixes the fingerprint (the
            # scheduler cache must never serve one mode's scheduler to
            # the other) and always rides the bucket so relax and ffd
            # problems can never coalesce into one vmapped batch.
            eff_mode = (
                solver_mode
                or problem.get("solver_mode")
                or self.default_mode
            )
            # brownout rung 1+ (ISSUE 17): relax traffic is served in FFD
            # mode — the anytime answer. The REQUEST is honored (a real
            # verified placement comes back, phases say mode=ffd), only
            # the iterative-refinement budget is browned out; the
            # verifier runs unchanged on every rung.
            if self.brownout_rung >= 1 and eff_mode == "relax":
                eff_mode = "ffd"
                m.SOLVERD_BROWNOUT_SERVED.inc(
                    {"rung": str(self.brownout_rung)}
                )
            problem["solver_mode"] = eff_mode
            # the codec fingerprint deliberately excludes the raw
            # mode field (a mode-less wire and an explicit default
            # must map to ONE cached scheduler); the RESOLVED mode
            # re-joins here so the cache stays mode-bound without
            # version-skew splits
            problem["fingerprint"] = (
                f"{problem['fingerprint']}+m{eff_mode}"
            )
            # the coalescer's compatibility key: the decoded problem's
            # compile-shape bucket (codec.problem_bucket) scoped to this
            # daemon's device count; the fingerprint keeps two requests
            # for the SAME problem off one grant (a cached DeviceScheduler
            # is single-solve stateful)
            ticket.bucket = (
                f"{problem['bucket']}|m{eff_mode}|d{self.devices}"
                f"|k{self.kernel}"
            )
            ticket.fingerprint = problem["fingerprint"]
            ticket.payload = (body, problem, digest)
        except BaseException:
            self.gateway.abandon(ticket)
            raise
        self.gateway.await_grant(ticket)  # may raise Shed/DrainError
        if ticket.batched_member:
            # a leader collected this request onto its grant (the one-way
            # marker, NOT the mutable state — release_batch may have
            # already flipped state to "done" before this thread woke,
            # and racing past that onto the leader path would run a solve
            # without holding the grant): wait for the per-problem
            # outcome (an isolated failure re-raises here and answers
            # alone), then encode on THIS handler thread — host fan-out,
            # the device is already on to the next grant
            results, dt = self.gateway.await_batched(ticket)
            self.quarantine.clear(digest)
            m.SOLVERD_TENANT_SOLVES.inc(
                {"tenant": ticket.tenant, "endpoint": "solve"}
            )
            return codec.encode_solve_results(results, dt), dt
        return self._solve_as_leader(ticket)

    def _scheduler_for(self, problem: dict, approx_bytes: int):
        """Fingerprint-keyed DeviceScheduler acquisition (cache hit or
        construction) — per problem, inside the device window, exactly as
        the pre-batching path charged it."""
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.models.provisioner import DeviceScheduler

        scheduler = self._sched_cache.get(problem["fingerprint"])
        if scheduler is None:
            m.SOLVERD_SCHED_CACHE.inc({"outcome": "miss"})
            scheduler = DeviceScheduler(
                problem["nodepools"],
                problem["instance_types"],
                existing_nodes=problem["existing_nodes"],
                daemonset_pods=problem["daemonset_pods"],
                max_slots=problem["max_slots"],
                topology=problem["topology"],
                unavailable_offerings=problem["unavailable_offerings"],
                devices=self.devices,
                solver_mode=(
                    problem.get("solver_mode") or self.default_mode
                ),
                kernel_backend=self.kernel,
                # the CLIENT verifies (solver/remote.py): it must not
                # trust the wire anyway, so a sidecar-side check would
                # double the overhead yet still miss wire corruption —
                # and a silent in-sidecar greedy degrade would hide
                # the rejection signal from the fleet's operators
                verify=False,
            )
            # the encoded request size is the entry's weight proxy: it
            # tracks catalog/node scale without walking device buffers
            self._sched_cache.put(
                problem["fingerprint"], scheduler, approx_bytes
            )
        else:
            m.SOLVERD_SCHED_CACHE.inc({"outcome": "hit"})
            # the fingerprint ignores the pod-derived excluded-uid
            # list; hand the cached scheduler this request's live
            # topology context so exclusions are never stale
            scheduler.update_topology_context(problem["topology"])
        return scheduler

    def _solve_as_leader(self, ticket):
        """The granted request's device phase: optionally wait the batch
        window, collect compatible queued problems, solve the whole batch
        under this one grant, distribute per-problem outcomes, encode our
        own. A batch of one is byte-for-byte the pre-batching solo path
        (solve_batch drives the same per-problem pipeline with the same
        donating kernels)."""
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.models import provisioner as prov

        # chaos draws AFTER the grant: a request that admission refused
        # (shed/drain/quarantine) must not consume a scripted fault it
        # will never execute — a consumed entry always fires. The fault
        # targets the LEADER's problem only, so the chaos tests exercise
        # the batch-isolation contract end-to-end.
        fault = self.chaos.next_fault() if self.chaos is not None else "ok"
        grant_t0 = time.perf_counter()
        members = []
        if self.gateway.max_batch > 1:
            window = self.gateway.batch_window
            limit = self.gateway.max_batch - 1
            if (
                window > 0
                and self.gateway.preparing() > 0
                and self.gateway.compatible_queued(ticket) < limit
            ):
                # solve requests are mid-decode on their handler threads
                # AND the batch is not already fillable from the queue:
                # hold the grant for the (few-ms, bounded) window so they
                # can reach the queue and coalesce instead of
                # serializing — waking EARLY the moment the decodes land
                # or the batch fills, so the window is a ceiling on
                # device idle, not a tax every grant pays in full
                w0 = time.perf_counter()
                deadline = w0 + window
                while True:
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    time.sleep(min(left, window / 8))
                    if (
                        self.gateway.preparing() == 0
                        or self.gateway.compatible_queued(ticket) >= limit
                    ):
                        break
                m.SOLVERD_BATCH_WINDOW_WAIT.observe(
                    time.perf_counter() - w0
                )
            members = self.gateway.collect_batch(ticket)
        batch = [ticket] + members
        digests = [t.payload[2] for t in batch]
        outcomes = [None] * len(batch)
        solve_wall = 0.0
        # pod-weighted fairness shares: a tenant whose problem brings 10x
        # the pods pays 10x the share of this grant's device seconds
        weights = [max(len(t.payload[1]["pods"]), 1) for t in batch]
        total_w = float(sum(weights))
        try:
            try:
                # journal breadcrumbs + watchdog INSIDE the try: begin()
                # does file I/O per digest, and a raise here with members
                # already collected but release never reached would wedge
                # the gateway (_active stuck) and hang every member's
                # done.wait() forever; in here, the finallys below
                # guarantee release_batch and the member drain sweep
                # (done()/disarm() are no-ops for digests never begun)
                for d in digests:
                    # graftlint: disable=GL304 -- deliberate tradeoff
                    # (ISSUE 8/9 review): begin() must run at grant time —
                    # journaling the digest any earlier would charge a
                    # crash strike against problems still sitting in the
                    # queue — and inside the release-guaranteeing try so a
                    # disk-full raise can never wedge the gateway. The
                    # write is a tmp+rename of a tiny JSON file; done()
                    # (the rewrite) stays off the window below.
                    self.quarantine.begin(d)
                if self.watchdog is not None:
                    self.watchdog.arm(
                        f"solve tenant={ticket.tenant} batch={len(batch)}"
                    )
                if fault.startswith("wedge"):
                    self.chaos.wedge(fault)  # holds the grant; watchdog trips
                entries, entry_idx = [], []
                for i, t in enumerate(batch):
                    if i == 0 and fault == "crash":
                        # device-phase raise -> poison strike, leader only
                        try:
                            self.chaos.crash()
                        except Exception as e:
                            outcomes[i] = ("error", e)
                            continue
                    body_i, problem_i, _d = t.payload
                    try:
                        # the cache's byte-bound weight comes from the
                        # PROBLEM's scale (resolved segment bytes for a
                        # manifest, body bytes for the full wire) — a
                        # steady-state manifest body is a few hundred
                        # bytes and would let N delta-wire tenants pin N
                        # full schedulers past the --cache-mib bound
                        # incremental path (incsolve, ISSUE 16): when the
                        # request names its predecessor and the engine is
                        # on, a lazy wrapper rides the batch entry — the
                        # engine replays the unchanged half of the prior
                        # packing and only constructs the real scheduler
                        # (through this same cache seam) when it decides
                        # it needs a fresh solve
                        if (
                            self.incremental is not None
                            and problem_i.get("prev_fingerprint")
                        ):
                            bytes_i = (
                                problem_i.get("approx_bytes") or len(body_i)
                            )
                            scheduler = self.incremental.wrap(
                                problem_i,
                                lambda p=problem_i, b=bytes_i: (
                                    self._scheduler_for(p, b)
                                ),
                            )
                        else:
                            scheduler = self._scheduler_for(
                                problem_i,
                                problem_i.get("approx_bytes") or len(body_i)
                            )
                    except Exception as e:
                        outcomes[i] = ("error", e)
                        continue
                    # relaxsolve anytime budget: the request's remaining
                    # client deadline bounds the optimizer's wall — past
                    # it the relax pass skips and the FFD answer serves
                    # (the PR 8 deadline machinery, one layer deeper).
                    # Reset, don't just set: the scheduler is cached per
                    # fingerprint, and a stale tiny budget left by a
                    # deadline-carrying request would permanently degrade
                    # deadline-less requests to the FFD answer.
                    if getattr(scheduler, "solver_mode", "ffd") == "relax":
                        scheduler.relax_budget_s = (
                            max(t.deadline_at - self.gateway.time_fn(), 0.0)
                            if t.deadline_at is not None
                            else None
                        )
                    entries.append((scheduler, problem_i["pods"]))
                    entry_idx.append(i)
                if entries:
                    t0 = time.perf_counter()
                    with self._maybe_profile():
                        solved, bstats = prov.solve_batch(entries)
                    solve_wall = time.perf_counter() - t0
                    for i, outcome in zip(entry_idx, solved):
                        outcomes[i] = outcome
                    if bstats["padded_total_rows"]:
                        m.SOLVERD_BATCH_PADDING.observe(
                            bstats["padded_rows"]
                            / bstats["padded_total_rows"]
                        )
                # count COMPLETED solves only (the pre-batching counter's
                # meaning — an errored problem never counted); handler
                # threads run concurrently, so a bare += would race
                ok_count = sum(
                    1 for o in outcomes if o is not None and o[0] == "ok"
                )
                with self._state_lock:
                    self.solves += ok_count
            finally:
                if self.watchdog is not None:
                    self.watchdog.disarm()
                # charge the FULL exclusive occupancy — window wait,
                # cache-miss scheduler construction, and the elapsed time
                # even when a solve raised: fairness and the admission
                # per-grant p50 must see what the device actually lost.
                # Each tenant pays its pod-weighted share of the grant; a
                # solo grant goes through the release() seam unchanged
                # (it IS a batch of one, and tests instrument that seam).
                occupancy = time.perf_counter() - grant_t0
                if len(batch) == 1:
                    self.gateway.release(ticket, occupancy)
                else:
                    self.gateway.release_batch(
                        [
                            (t, w / total_w)
                            for t, w in zip(batch, weights)
                        ],
                        occupancy,
                    )
                # journal bookkeeping AFTER release: done() rewrites the
                # journal file, and file I/O must never ride the
                # exclusive device window
                for d in digests:
                    self.quarantine.done(d)
            # per-problem epilogue (host phase): strikes for isolated
            # device failures, success bookkeeping, member handoff — the
            # member threads do their own encodes
            for i, t in enumerate(batch):
                st, val = outcomes[i] or (
                    "error", RuntimeError("batch solve aborted"),
                )
                # per-problem device share of the batch wall, so every
                # response's X-Solver-Seconds sums to the real device time
                dt_i = solve_wall * weights[i] / total_w
                if st == "error":
                    # a device-phase failure is a poison strike against
                    # THAT problem's digest only — batch-mates unaffected
                    self.quarantine.strike(t.payload[2], "crash")
                    if i > 0:
                        self.gateway.finish_batched(t, error=val)
                elif i > 0:
                    self.gateway.finish_batched(t, result=(val, dt_i))
            st, val = outcomes[0] or (
                "error", RuntimeError("batch solve aborted"),
            )
            if st == "error":
                raise val
            results = val
            leader_dt = solve_wall * weights[0] / total_w
            self.quarantine.clear(ticket.payload[2])
            m.SOLVERD_TENANT_SOLVES.inc(
                {"tenant": ticket.tenant, "endpoint": "solve"}
            )
            # host phase again: encode outside the grant, the next
            # tenant's device phase is already running
            if fault == "bad_result":
                self.chaos.sabotage(results)  # verification-failing result
            out = codec.encode_solve_results(results, leader_dt)
            if fault == "corrupt_wire":
                out = self.chaos.corrupt(out)
            return out, leader_dt
        finally:
            # no member handler may wait forever: whatever path got here
            # (watchdog drain, an unexpected raise above), any member not
            # yet answered gets the drain contract (503 — the client
            # degrades to greedy WITHOUT charging its breaker; the member
            # request did not fail on its own problem)
            for t in batch[1:]:
                if not t.done.is_set():
                    self.gateway.finish_batched(
                        t, error=fleet.DrainError("batch leader aborted")
                    )

    def _decode_solve(self, body: bytes) -> dict:
        """The solve request's host-phase decode — a named seam so chaos
        tests can wedge ONE tenant's host phase and prove the device keeps
        serving everyone else. Manifest bodies resolve through the
        segment store here, pre-grant: a miss raises
        segments.SegmentMissError, the ticket is abandoned, and the HTTP
        layer answers the typed 409 — segment traffic never holds the
        device."""
        return codec.decode_solve_request(
            body, segment_store=self.segment_store
        )

    def _maybe_profile(self):
        """jax.profiler trace context when profiling is toggled on and a
        --profile-dir was configured; a no-op context otherwise. Lets TPU
        traces be captured from a RUNNING sidecar (POST /profile) without
        a redeploy."""
        import contextlib

        if self.profiling and self.profile_dir:
            import jax.profiler

            return jax.profiler.trace(self.profile_dir)
        return contextlib.nullcontext()

    def toggle_profile(self, enable: bool = None) -> dict:
        # read-modify-write (enable=None flips the current state) under its
        # own small lock: two concurrent POST /profile toggles must not both
        # read the same old value. Deliberately NOT a gateway ticket — a
        # toggle must not queue behind a multi-second solve.
        with self._state_lock:
            if enable is None:
                enable = not self.profiling
            self.profiling = bool(enable) and self.profile_dir is not None
            return {
                "profiling": self.profiling,
                "profile_dir": self.profile_dir,
                "configured": self.profile_dir is not None,
            }

    def consolidate(
        self, body: bytes, tenant: str = None, deadline: float = None
    ):
        """Consolidation sweeps ride the gateway's NORMAL lane: under
        contention every pending provisioning solve dispatches first.

        Same poison-quarantine protection as solve(): a frontier problem
        that wedges or crashes the device step is exactly as capable of
        crash-looping the shared sidecar as a solve problem, so its body
        digest is checked pre-decode, journaled around the device phase,
        and struck on a device-phase exception."""
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.models.consolidation import frontier_core

        digest = codec.request_digest(
            body, segment_store=self.segment_store
        )
        if self.quarantine.quarantined(digest):
            m.SOLVER_QUARANTINE_ROUTED.inc({"site": "gateway"})
            raise fleet.QuarantinedError(digest)
        ticket = self.gateway.submit(
            tenant or fleet.DEFAULT_TENANT, fleet.LANE_SWEEP, deadline
        )
        try:
            req = codec.decode_frontier_request(body)
            if tenant is None:
                ticket.tenant = req["tenant"]
        except BaseException:
            self.gateway.abandon(ticket)
            raise
        self.gateway.await_grant(ticket)
        dt = 0.0
        grant_t0 = time.perf_counter()
        # graftlint: disable=GL304 -- same deliberate tradeoff as the
        # solve path: the in-flight journal write belongs at grant time
        # (earlier would strike queued problems at a crash) and its
        # tmp+rename of a tiny file is bounded; done() runs post-release.
        self.quarantine.begin(digest)
        if self.watchdog is not None:
            self.watchdog.arm(f"consolidate tenant={ticket.tenant}")
        try:
            t0 = time.perf_counter()
            frontier = frontier_core(
                req["nodepools"],
                req["instance_types"],
                req["cand_nodes"],
                req["keep_nodes"],
                req["daemonset_pods"],
                req["base_pods"],
                req["candidate_pods"],
                max_slots=req["max_slots"],
                devices=self.devices,
            )
            dt = time.perf_counter() - t0
        except BaseException:
            self.quarantine.strike(digest, "crash")
            raise
        finally:
            if self.watchdog is not None:
                self.watchdog.disarm()
            # full-occupancy charge, as in solve()
            self.gateway.release(ticket, time.perf_counter() - grant_t0)
            self.quarantine.done(digest)  # after release, as in solve()
        self.quarantine.clear(digest)
        m.SOLVERD_TENANT_SOLVES.inc(
            {"tenant": ticket.tenant, "endpoint": "consolidate"}
        )
        return codec.encode_frontier_response(frontier), dt

    def health(self) -> dict:
        """The /healthz body: liveness (warm-up finished) + readiness
        (liveness AND the admission queue below its bound AND not
        draining). An overloaded sidecar is alive-but-unready — the
        supervisor must not respawn it into a load spike (a restart storm
        turns overload into outage); a DRAINING one is alive-but-leaving,
        reported so probes don't mistake the planned exit for a death."""
        depth = self.gateway.depth()
        saturated = self.gateway.saturated()
        draining = self.gateway.draining()
        return {
            "ok": self.ready,
            "ready": bool(self.ready and not saturated and not draining),
            "overloaded": saturated,
            "draining": draining,
            "queue_depth": depth,
            "queue_capacity": self.gateway.max_depth,
            # delta-wire surface (ISSUE 14): the boot identity clients key
            # their sent-caches on, and the segment store's residency so a
            # fleet dashboard can tell "cold member" from "evicting"
            "instance": self.instance,
            "segments": self.segment_store.stats(),
            # the poison ledger, so a fleet dashboard can tell "this
            # sidecar is refusing a poison problem" from "cold"
            "quarantine_entries": self.quarantine.size(),
            "watchdog_trips": (
                self.watchdog.trips if self.watchdog is not None else 0
            ),
            # brownout ladder rung (ISSUE 17): 0 = clear; 1 = relax
            # served as FFD; 2 = + widened batch window; 3 = + halved
            # admission capacity — a metric-labeled state, never a
            # verification change
            "brownout_rung": self.brownout_rung,
            # which FFD-scan kernel this daemon answers with (ISSUE 18,
            # --kernel): results are byte-identical across kernels, so
            # this is a performance-dashboard fact, not a routing one
            "kernel": self.kernel,
            # continuous-batching stats: how much device serialization the
            # coalescer is currently buying back (mean problems per grant,
            # lifetime coalesced count, the configured window/size bounds)
            "batch": self.gateway.batch_stats(),
            # incremental re-solve (incsolve, ISSUE 16): ledger residency
            # + drift-controller config + the last solve's outcome, so a
            # fleet dashboard can tell "warm ledger" from "amnesiac"
            "incremental": (
                self.incremental.stats()
                if self.incremental is not None
                else {"enabled": False}
            ),
        }

    # -- boot warm-up ------------------------------------------------------

    def warm_up(self, prewarm: bool = False) -> None:
        """Compile-cache bootstrap: always point XLA's persistent cache at
        the repo-local directory; with ``prewarm`` also run the synthetic
        shape-bucket solves so a restarted sidecar serves its first real
        batch from the jit cache instead of a compile cliff."""
        from karpenter_core_tpu.utils.jaxenv import (
            enable_persistent_compile_cache,
        )

        enable_persistent_compile_cache()
        if prewarm:
            from karpenter_core_tpu.api.nodepool import NodePool, NodePoolSpec
            from karpenter_core_tpu.api.objects import ObjectMeta
            from karpenter_core_tpu.cloudprovider.kwok import build_catalog
            from karpenter_core_tpu.models.provisioner import DeviceScheduler

            pool = NodePool(metadata=ObjectMeta(name="prewarm"))
            pool.spec = NodePoolSpec()
            catalog = build_catalog(cpu_grid=[1, 2, 4, 8], mem_factors=[2, 4])
            DeviceScheduler(
                [pool], {"prewarm": catalog}, max_slots=256,
                devices=self.devices,
                kernel_backend=self.kernel,
                # same sidecar contract as the solve path: the CLIENT is
                # the trust anchor, and a synthetic warm-up solve must
                # never bump the fleet's rejection metric from inside boot
                verify=False,
            ).prewarm()
        self.ready = True


class _Handler(BaseHTTPRequestHandler):
    server_version = "karpenter-solverd/1"
    daemon: SolverDaemon

    def log_message(self, *args) -> None:  # quiet
        pass

    def do_GET(self) -> None:
        path, _, query = self.path.partition("?")
        if path == "/statz":
            # the gateway snapshot (per-tenant queue-wait percentiles,
            # shed counts, depth, draining): the autoscaler's control
            # signal. ?reset=1 makes the window per-poll — the
            # autoscaler is the sole consumer of the reset form.
            from urllib.parse import parse_qs

            reset = parse_qs(query).get("reset", ["0"])[0] not in (
                "0", "false", "off",
            )
            return send_body(
                self, 200,
                json.dumps(
                    self.daemon.gateway.snapshot(reset=reset)
                ).encode(),
            )
        if path == "/healthz":
            health = self.daemon.health()
            send_body(
                self,
                200 if health["ok"] else 503,
                json.dumps(health).encode(),
            )
        elif path == "/metrics":
            from karpenter_core_tpu.metrics.registry import REGISTRY

            send_body(
                self, 200, REGISTRY.render().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/profile":
            send_body(
                self, 200,
                json.dumps(self.daemon.toggle_profile(
                    self.daemon.profiling  # GET reports, never toggles
                )).encode(),
            )
        else:
            send_body(self, 404, b'{"error": "not found"}')

    def _request_identity(self):
        """(tenant, deadline, solver_mode) from transport headers. The
        header is the gateway's pre-decode identity; the wire's tenant
        field backs it up for header-less clients. A malformed deadline
        means no deadline (shedding on garbage would turn a client bug
        into an outage); an unknown X-Solver-Mode is ignored the same
        way — the wire field / daemon default decide instead."""
        tenant = self.headers.get("X-Solver-Tenant") or None
        deadline = None
        raw = self.headers.get("X-Solver-Deadline")
        if raw:
            try:
                deadline = float(raw)
            except ValueError:
                deadline = None
        if deadline is not None and deadline <= 0:
            deadline = None
        from karpenter_core_tpu.solver import codec as _codec

        mode = self.headers.get("X-Solver-Mode") or None
        if mode is not None and mode not in _codec.SOLVER_MODES:
            mode = None
        return tenant, deadline, mode

    def do_POST(self) -> None:
        path, _, query = self.path.partition("?")
        body = read_body(self)
        tenant, deadline, solver_mode = self._request_identity()
        try:
            if path == "/solve":
                out, dt = self.daemon.solve(
                    body, tenant=tenant, deadline=deadline,
                    solver_mode=solver_mode,
                )
            elif path == "/consolidate":
                out, dt = self.daemon.consolidate(
                    body, tenant=tenant, deadline=deadline
                )
            elif path == "/profile":
                from urllib.parse import parse_qs

                q = parse_qs(query)
                enable = None
                if "enable" in q:
                    enable = q["enable"][0] not in ("0", "false", "off")
                state = self.daemon.toggle_profile(enable)
                return send_body(self, 200, json.dumps(state).encode())
            elif path == "/drain":
                # supervisor-initiated clean restart: stop admission,
                # flush the queue (503s), exit with DRAIN_EXIT_CODE once
                # the in-flight device step clears
                state = self.daemon.drain()
                return send_body(self, 200, json.dumps(state).encode())
            elif path == "/brownout":
                # autoscaler-driven ladder transition (ISSUE 17)
                try:
                    req = json.loads(body or b"{}")
                    state = self.daemon.set_brownout(
                        int(req.get("rung", 0))
                    )
                except (ValueError, TypeError):
                    return send_body(
                        self, 400, b'{"error": "bad brownout rung"}'
                    )
                return send_body(self, 200, json.dumps(state).encode())
            else:
                return send_body(self, 404, b'{"error": "not found"}')
        except fleet.ShedError as e:
            # overload is a CONTRACT, not an error: 429 + the gateway's
            # retry estimate; the client degrades this solve to greedy
            return send_body(
                self, 429,
                json.dumps(
                    {"error": "overloaded", "reason": e.reason}
                ).encode(),
                headers={"Retry-After": f"{e.retry_after:.3f}"},
            )
        except fleet.DrainError:
            # draining is a CONTRACT too: 503 says "restarting, answer
            # came from a live process" — the client degrades this solve
            # to greedy without charging its breaker
            return send_body(
                self, 503, b'{"error": "draining"}',
            )
        except fleet.QuarantinedError as e:
            # poison pill: refused pre-decode; 422 tells the client to
            # quarantine locally and route straight to greedy
            return send_body(
                self, 422,
                json.dumps({
                    "error": "quarantined",
                    "fingerprint": e.fingerprint,
                }).encode(),
            )
        except segments.SegmentMissError as e:
            # delta-wire typed miss (ISSUE 14): the store cannot produce
            # these digests — answer 409 naming them (+ our instance id,
            # what the client's sent-cache rebinds on) and the client
            # repairs with ONE upload round. Never a wrong solve, never a
            # breaker charge: a miss is an answer, not a fault.
            return send_body(
                self, 409,
                json.dumps({
                    "error": "segments_missing",
                    "need": e.need,
                    "instance": self.daemon.instance,
                }).encode(),
            )
        except Exception as e:
            return send_body(
                self, 500, repr(e).encode(), ctype="text/plain"
            )
        send_body(
            self, 200, out, _OCTET,
            headers={
                "X-Solver-Seconds": f"{dt:.6f}",
                "X-Solverd-Instance": self.daemon.instance,
            },
        )


def serve(
    port: int,
    host: str = "127.0.0.1",
    daemon: SolverDaemon = None,
    ready: bool = True,
) -> ThreadingHTTPServer:
    """Serve solverd on host:port in a daemon thread; returns the server
    (port 0 picks a free one — server_address[1]). ``ready=True`` marks the
    daemon ready immediately (in-thread test servers skip warm-up)."""
    d = daemon or SolverDaemon()
    if ready:
        d.ready = True
    handler = type("BoundSolverd", (_Handler,), {"daemon": d})
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.daemon_ = d
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description="karpenter TPU solver sidecar")
    ap.add_argument("--port", type=int, default=8181)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument(
        "--prewarm", action="store_true",
        help="compile the common shape buckets before serving traffic",
    )
    ap.add_argument(
        "--profile-dir", default=None,
        help="directory for jax.profiler traces; solves are wrapped in a"
        " trace capture while profiling is toggled on via POST /profile"
        " (off by default), so TPU-side traces can be grabbed from a"
        " running sidecar without redeploying",
    )
    ap.add_argument(
        "--queue-depth", type=int, default=fleet.DEFAULT_QUEUE_DEPTH,
        help="admission bound: requests in flight (queued + host phase +"
        " device) before the gateway sheds with 429 + Retry-After",
    )
    ap.add_argument(
        "--tenant-weights", default="",
        help="fair-share weights as 'tenant=weight,...' (default weight 1:"
        " a weight-3 tenant gets ~3x the device share under contention)",
    )
    ap.add_argument(
        "--cache-entries", type=int, default=fleet.DEFAULT_CACHE_ENTRIES,
        help="DeviceScheduler cache entry bound (one entry per distinct"
        " problem fingerprint across all tenants)",
    )
    ap.add_argument(
        "--cache-mib", type=int,
        default=fleet.DEFAULT_CACHE_BYTES >> 20,
        help="DeviceScheduler cache approximate-byte bound, in MiB"
        " (encoded-request-size proxy per entry)",
    )
    ap.add_argument(
        "--max-batch", type=int, default=fleet.DEFAULT_MAX_BATCH,
        help="continuous batching: max compatible queued problems one"
        " device grant may solve as a single vmapped batch (1 disables"
        " coalescing — every problem gets its own exclusive grant)",
    )
    ap.add_argument(
        "--batch-window-ms", type=float,
        default=fleet.DEFAULT_BATCH_WINDOW_MS,
        help="continuous batching: max milliseconds a grant leader holds"
        " the device waiting for still-decoding requests to reach the"
        " queue (bounds the latency cost of coalescing; 0 = never wait,"
        " coalesce only what is already queued)",
    )
    ap.add_argument(
        "--devices", type=int, default=1,
        help="shard every solve/sweep over the first N local devices"
        " (pjit over the slot axis; 0 = all local devices, 1 ="
        " single-device). Requests clamp to what exists, so a slice"
        " config degrades to single-device on a 1-chip box",
    )
    ap.add_argument(
        "--watchdog-seconds", type=float, default=120.0,
        help="hard wall-clock bound on the exclusive device step; on"
        " overrun the process drains its queue (503s) and exits"
        " crash-only for the supervisor to respawn (0 disables)",
    )
    ap.add_argument(
        "--quarantine-strikes", type=int,
        default=fleet.QUARANTINE_STRIKES,
        help="device-phase faults a problem digest may accumulate inside"
        " the quarantine TTL before the sidecar refuses it with 422",
    )
    ap.add_argument(
        "--quarantine-ttl", type=float, default=fleet.QUARANTINE_TTL,
        help="seconds a quarantined poison-pill digest stays refused",
    )
    ap.add_argument(
        "--solver-mode", choices=list(codec.SOLVER_MODES), default="ffd",
        help="solve backend served when a request names none: ffd ="
        " first-fit-decreasing (classic), relax = convex-relaxation"
        " optimizer with the FFD result as the scored/anytime fallback;"
        " requests override per-call via the wire field or the"
        " X-Solver-Mode header",
    )
    ap.add_argument(
        "--kernel", choices=("xla", "pallas"), default="xla",
        help="FFD-scan kernel implementation: xla = classic per-op"
        " lowering of ops/ffd.py, pallas = the hand-fused per-class"
        " kernel (ops/pallas_ffd.py, slot state resident in VMEM across"
        " the fused stages; interpreted off-TPU). Byte-identical results"
        " either way — a latency lever, not a semantics switch",
    )
    ap.add_argument(
        "--segment-cache-mib", type=int,
        default=segments.DEFAULT_STORE_BYTES >> 20,
        help="delta-wire segment store byte bound, in MiB (canonical"
        " segment bytes; LRU past it — an evicted segment costs the next"
        " manifest one miss/re-upload round, never a wrong solve)",
    )
    ap.add_argument(
        "--segment-ttl", type=float, default=segments.DEFAULT_STORE_TTL,
        help="idle seconds before a segment no manifest references"
        " expires from the store (references refresh it)",
    )
    ap.add_argument(
        "--no-incremental", action="store_true",
        help="disable the incremental re-solve engine: every request"
        " solves fresh even when it names a prev_fingerprint (the"
        " packing ledger is never consulted or populated)",
    )
    ap.add_argument(
        "--incremental-interval", type=int,
        default=incsolve.DEFAULT_FULL_INTERVAL,
        help="drift controller: force a full solve after this many"
        " consecutive warm/partial replays of one problem lineage, so"
        " incremental packings cannot ratchet into bad node sets",
    )
    ap.add_argument(
        "--incremental-max-dirty", type=float,
        default=incsolve.DEFAULT_MAX_DIRTY_FRACTION,
        help="proportionality bound: past this dirty-pod fraction the"
        " engine skips the replay and solves fresh (diff bookkeeping"
        " stops paying for itself)",
    )
    ap.add_argument(
        "--ledger-entries", type=int,
        default=incsolve.DEFAULT_MAX_ENTRIES,
        help="packing ledger entry bound (one remembered packing per"
        " mode-suffixed problem fingerprint, LRU past it)",
    )
    ap.add_argument(
        "--ledger-mib", type=int,
        default=incsolve.DEFAULT_MAX_BYTES >> 20,
        help="packing ledger approximate-byte bound, in MiB (uid/name"
        " reference accounting per entry)",
    )
    ap.add_argument(
        "--quarantine-journal", default=None,
        help="path for the crash-only poison journal: the digest in"
        " flight on the device is recorded here, so a problem that"
        " KILLS the process is charged its strike by the respawned"
        " child (no journal = in-memory quarantine only)",
    )
    args = ap.parse_args()
    if args.devices < 0:
        ap.error("--devices must be >= 0 (0 = all local devices)")
    if args.watchdog_seconds < 0:
        ap.error("--watchdog-seconds must be >= 0 (0 disables)")
    if args.max_batch < 1:
        ap.error("--max-batch must be >= 1 (1 disables coalescing)")
    if args.batch_window_ms < 0:
        ap.error("--batch-window-ms must be >= 0 (0 = never wait)")
    if args.segment_cache_mib <= 0:
        ap.error("--segment-cache-mib must be positive")
    if args.segment_ttl <= 0:
        ap.error("--segment-ttl must be positive")
    if args.incremental_interval < 1:
        ap.error("--incremental-interval must be >= 1")
    if not (0.0 <= args.incremental_max_dirty <= 1.0):
        ap.error("--incremental-max-dirty must be in [0, 1]")
    if args.ledger_entries < 1 or args.ledger_mib < 1:
        ap.error("--ledger-entries/--ledger-mib must be positive")

    daemon = SolverDaemon(
        profile_dir=args.profile_dir,
        gateway=fleet.FleetGateway(
            max_depth=args.queue_depth,
            weights=fleet.parse_tenant_weights(args.tenant_weights),
            max_batch=args.max_batch,
            batch_window=args.batch_window_ms / 1000.0,
        ),
        sched_cache=fleet.BoundedSchedulerCache(
            max_entries=args.cache_entries,
            max_bytes=args.cache_mib << 20,
        ),
        devices=args.devices,
        watchdog_seconds=args.watchdog_seconds,
        default_mode=args.solver_mode,
        kernel=args.kernel,
        segment_store=segments.SegmentStore(
            max_bytes=args.segment_cache_mib << 20,
            ttl=args.segment_ttl,
        ),
        incremental=(
            False
            if args.no_incremental
            else incsolve.IncrementalEngine(
                ledger=incsolve.PackingLedger(
                    max_entries=args.ledger_entries,
                    max_bytes=args.ledger_mib << 20,
                ),
                full_interval=args.incremental_interval,
                max_dirty_fraction=args.incremental_max_dirty,
            )
        ),
        quarantine=fleet.PoisonQuarantine(
            strikes=args.quarantine_strikes,
            ttl=args.quarantine_ttl,
            site="gateway",
            journal_path=args.quarantine_journal,
        ),
        # the real sidecar exits crash-only on watchdog trip / drain; the
        # supervisor's exit-code contract does the rest
        exit_fn=os._exit,
    )
    httpd = serve(args.port, host=args.host, daemon=daemon, ready=False)
    # the supervisor (solver/supervisor.py) reads this line to learn the
    # bound address — same handshake as kube/httpserver.py
    print(
        f"listening on {httpd.server_address[0]}:{httpd.server_address[1]}",
        flush=True,
    )
    daemon.warm_up(prewarm=args.prewarm)
    print("ready", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        httpd.server_close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
