"""Operator CLI entry point (reference: kwok/main.go:28-47).

Builds the full controller stack over the in-memory store + kwok provider
and runs the reconcile loop. Flags/env parse through Options.parse
(--solver greedy|tpu, --solver-mode inproc|sidecar, --solver-backend
ffd|relax, --kernel xla|pallas (FFD-scan kernel implementation:
hand-fused Pallas hot core vs classic XLA lowering — byte-identical
results, a latency lever), --solver-addr,
--solver-timeout, --solver-verify true|false (host-side verification of
every device/sidecar result — on by default), --batch-max-duration,
--batch-idle-duration, --log-level, --feature-gates Name=true,...), plus
loop controls:
--poll-interval seconds between passes, --max-iters to bound the run
(0 = run until interrupted).

    python -m karpenter_core_tpu.main --solver tpu --log-level debug
    python -m karpenter_core_tpu.main --solver tpu --solver-mode sidecar
"""
from __future__ import annotations

import sys
import time
from typing import List, Optional

from karpenter_core_tpu.logging import configure
from karpenter_core_tpu.operator import Operator, Options


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    options = Options.parse(argv)
    logger = configure(options.log_level)

    op = Operator(options=options)
    health = None
    if options.health_port:
        from karpenter_core_tpu.healthserver import start_health_server

        port = 0 if options.health_port < 0 else options.health_port
        health = start_health_server(op, port)
        # log the ACTUAL listen address — the server binds 0.0.0.0 by
        # default, not loopback
        logger.info(
            "health/metrics on %s:%d (/healthz /readyz /metrics)",
            health.server_address[0],
            health.server_address[1],
        )
    if op.solver_client is not None:
        logger.info("solver sidecar at %s", op.solver_client.addr)
    logger.info(
        "operator starting: solver=%s mode=%s batch=%ss/%ss gates=%s",
        options.solver,
        options.solver_mode,
        options.batch_max_duration,
        options.batch_idle_duration,
        options.feature_gates,
    )
    n = 0
    try:
        while True:
            op.reconcile_once()
            n += 1
            if options.max_iters and n >= options.max_iters:
                break
            time.sleep(options.poll_interval)
    except KeyboardInterrupt:
        logger.info("operator interrupted after %d passes", n)
    finally:
        op.shutdown()
        if health is not None:
            health.shutdown()
            health.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
