"""gangsched: priority-preemptive packing and gang-atomic placement.

The FFD scan (ops/ffd.py) packs a flat bag of pod classes; this layer
makes two workload shapes first-class solver scenarios (ROADMAP item 3):

* **Priority tiers with simulated preemption** — classes arrive tier-
  ordered high→low (models/provisioner._sorted_classes lifts
  utils/disruption.priority_tier to the class order), so within one solve
  a lower tier can never starve a higher one. When a positive-tier class
  STILL cannot place, ``preempt_pass`` treats strictly-lower-tier pods
  already bound on existing nodes as evictable capacity: per node, the
  cheapest sufficient PREFIX of its cost-ordered evictable pods is
  priced by a vmapped prefix-fit (cumulative freed capacity → pods
  admitted), and nodes are claimed cheapest-cost-per-admitted-pod first —
  minimal-cost by construction at both levels ("Priority Matters",
  PAPERS.md). The selected eviction set returns with the packing as
  eviction claims the operator turns into drain-before-bind.

* **Gang atomicity** — a gang axis rides the class batch (``gang_of_step``
  maps scan steps to gangs, ``gang_min`` carries each gang's min-count).
  ``gang_solve`` runs the scan, measures each gang's placed count, and
  ROLLS BACK every gang below its min on device: requirement-plane
  intersections are not invertible, so the rollback is a second
  ``lax.cond``-gated scan from the same init state with failed gangs'
  counts zeroed — no host round-trip, and the common all-gangs-commit case
  pays only a segment-sum. A second-order cascade (a gang that only
  committed because a failed gang's takes warped later placements) is
  caught by a final mask: its takes zero and the whole group reports
  unschedulable (the host backstop in solver/gangs.enforce_atomicity
  covers the decode seam the same way).

Off by default: these kernels only dispatch when the problem carries
non-zero tiers or gangs (models/provisioner gates on the class batch), so
plain problems run the exact pre-gang entries and produce byte-identical
result wires.

Interplay limits (documented, verifier-enforced): the preemption pass
serves positive-tier, gang-free classes in solves WITHOUT device topology
state (a preempted placement bypasses the in-kernel topology counters);
gang rollback composes with everything.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from karpenter_core_tpu.ops.ffd import (
    BIG,
    BIGI,
    ClassStep,
    FFDStatics,
    SlotState,
    _class_slot_compatible,
    _ffd_solve_impl,
)
from karpenter_core_tpu.solver.gangs import GANG_FREE

# Preemption fan-out bound: one class's remaining pods spread over at most
# this many preempted nodes per solve (a lax.scan length, so it is a
# compile-time constant). Demands wider than this stay unschedulable —
# bounded, predictable kernel cost beats an unbounded eviction sweep.
NODE_ROUNDS = 8


class EvPlanes(NamedTuple):
    """Evictable bound pods per existing slot, cost-sorted.

    Host prep (models/provisioner) sorts each node's evictable pods by
    (disruption cost, uid) ascending and pads the pod axis to P; the
    kernel masks by tier at use. Adding a field? Classify its slot-axis
    placement in parallel/mesh.GANG_EV_SPECS (the GL501/GL503 routing).
    """

    req: jax.Array  # [N, P, R] float32 — quantized freed-capacity vectors
    tier: jax.Array  # [N, P] int32 (pad: BIGI — never strictly lower)
    cost: jax.Array  # [N, P] float32 — utils/disruption.eviction_cost
    valid: jax.Array  # [N, P] bool


# ---------------------------------------------------------------------------
# gang-atomic solve


def _gang_failures(takes, gang_of_step, gang_min):
    """[G] bool — gangs whose placed count missed their min."""
    G = gang_min.shape[0]
    placed_step = jnp.sum(takes, axis=1)  # [J]
    seg = jnp.where(gang_of_step >= 0, gang_of_step, G)
    placed_g = jax.ops.segment_sum(
        placed_step, seg, num_segments=G + 1
    )[:G]
    # padded gangs carry min 0: 0 < 0 is False, so they never "fail"
    return placed_g < gang_min


def _gang_solve_impl(state: SlotState, classes: ClassStep,
                     statics: FFDStatics, gang_of_step, gang_min,
                     level_iters: int):
    final1, takes1, unplaced1 = _ffd_solve_impl(
        state, classes, statics, level_iters
    )
    failed = _gang_failures(takes1, gang_of_step, gang_min)
    step_failed = jnp.where(
        gang_of_step >= 0, failed[jnp.clip(gang_of_step, 0)], False
    )
    any_failed = jnp.any(step_failed)

    def rerun(_):
        # the on-device rollback: re-solve from the SAME init state with
        # failed gangs inert (count 0 places nothing and perturbs no
        # state) — intersection-based requirement planes cannot be
        # un-merged, so rollback IS a re-solve
        classes2 = classes._replace(
            count=jnp.where(step_failed, 0, classes.count)
        )
        return _ffd_solve_impl(state, classes2, statics, level_iters)

    def keep(_):
        return final1, takes1, unplaced1

    final, takes, unplaced = jax.lax.cond(any_failed, rerun, keep, None)

    # second-order cascade guard: a gang whose pass-1 commit depended on a
    # rolled-back gang's takes can fail in pass 2 — zero its takes and
    # report the group unschedulable rather than scanning forever. Slot
    # planes keep the (tighter-than-needed) intersections; decode treats
    # any resulting divergence through the host repair path, and the
    # atomicity backstop re-checks the final Results.
    failed2 = _gang_failures(takes, gang_of_step, gang_min)
    step_failed2 = jnp.where(
        gang_of_step >= 0, failed2[jnp.clip(gang_of_step, 0)], False
    )
    dropped = step_failed | step_failed2
    takes = jnp.where(dropped[:, None], 0, takes)
    # one unschedulable report per class, on its (sub_)last step — the
    # step->class aggregation sums unplaced per class
    unplaced = jnp.where(
        dropped, jnp.where(classes.sub_last, classes.count, 0), unplaced
    )
    return final, takes, unplaced


# graftlint: disable=GL103 -- deliberately non-donating: the parity tests
# re-drive the same init state; the production path uses the donating twin
gang_solve = partial(jax.jit, static_argnames=("level_iters",))(
    _gang_solve_impl
)

# Donating twin (the production path): same lazy CPU-aliasing probe as
# ops/ffd.ffd_solve_donated — donation is a no-op on CPU and the backend
# probe must not initialize XLA at import time. The init state is used by
# BOTH conditional scans inside one jit; XLA owns the internal aliasing.
_gang_donated_impl = None


def gang_solve_donated(state: SlotState, classes: ClassStep,
                       statics: FFDStatics, gang_of_step, gang_min,
                       level_iters: int = 32):
    global _gang_donated_impl
    if _gang_donated_impl is None:
        if jax.default_backend() != "cpu":
            _gang_donated_impl = partial(
                jax.jit, static_argnames=("level_iters",), donate_argnums=(0,)
            )(_gang_solve_impl)
        else:
            _gang_donated_impl = gang_solve
    return _gang_donated_impl(
        state, classes, statics, gang_of_step, gang_min,
        level_iters=level_iters,
    )


def _gang_solve_batched_impl(state, classes, statics, gang_of_step,
                             gang_min, level_iters: int):
    return jax.vmap(
        lambda s, c, st, g, gm: _gang_solve_impl(s, c, st, g, gm, level_iters)
    )(state, classes, statics, gang_of_step, gang_min)


# Batched twin for the continuous-batching driver (solve_batch): gang
# problems coalesce only with gang problems of identical compile shapes
# (the _KernelRequest shape key covers the gang tensors), and the stacked
# state must still route through parallel.mesh batched placement.
# graftlint: disable=GL103 -- non-donating twin, mirrors ffd_solve_batched
gang_solve_batched = partial(jax.jit, static_argnames=("level_iters",))(
    _gang_solve_batched_impl
)

_gang_batched_donated_impl = None


def gang_solve_batched_donated(state, classes, statics, gang_of_step,
                               gang_min, level_iters: int = 32):
    global _gang_batched_donated_impl
    if _gang_batched_donated_impl is None:
        if jax.default_backend() != "cpu":
            _gang_batched_donated_impl = partial(
                jax.jit, static_argnames=("level_iters",), donate_argnums=(0,)
            )(_gang_solve_batched_impl)
        else:
            _gang_batched_donated_impl = gang_solve_batched
    return _gang_batched_donated_impl(
        state, classes, statics, gang_of_step, gang_min,
        level_iters=level_iters,
    )


# ---------------------------------------------------------------------------
# the preemption pass


def _node_prefix_fit(avail_n, elig_n, req_n, cost_n, r):
    """One node's eviction price curve (vmapped over the slot axis):
    cumulative freed capacity over the cost-ordered eligible prefix →
    (kfit [P+1] pods admitted after evicting the first j, cost [P+1]
    cumulative cost of that prefix). j=0 is eviction-free residual fit."""
    P = elig_n.shape[0]
    freed = jnp.cumsum(jnp.where(elig_n[:, None], req_n, 0.0), axis=0)
    freed0 = jnp.concatenate(
        [jnp.zeros((1, req_n.shape[1]), req_n.dtype), freed], axis=0
    )  # [P+1, R]
    coste = jnp.cumsum(jnp.where(elig_n, cost_n, 0.0))
    cost0 = jnp.concatenate([jnp.zeros((1,), coste.dtype), coste])
    safe_r = jnp.where(r > 0, r, 1.0)
    head = (avail_n[None, :] + freed0) / safe_r[None, :]
    head = jnp.where(r[None, :] > 0, head, BIG)
    kfit = jnp.floor(jnp.min(head, axis=-1))  # [P+1]
    return jnp.clip(kfit, 0.0, 2**30).astype(jnp.int32), cost0


def _preempt_impl(state: SlotState, classes: ClassStep,
                  statics: FFDStatics, step_tier, step_gang, unplaced,
                  ev: EvPlanes, node_rounds: int):
    """Serve still-unplaced positive-tier gang-free classes from evictable
    capacity. Scans the class axis with an (evicted, capacity-bonus)
    carry; per class, the vmapped per-node prefix-fit prices every node
    and a bounded greedy claims nodes cheapest-cost-per-admitted-pod
    first. Returns (extra takes [J, N], unplaced' [J], evicted [N, P])."""
    N, P = ev.tier.shape

    def class_step(carry, xs):
        evicted, bonus = carry
        c, tier_j, gang_j, m0 = xs
        # gang-free is exactly GANG_FREE: GANG_FALLBACK_STRADDLING marks a
        # member of a gang whose atomicity is host-enforced — evicting for
        # it could strand claims if the backstop strips the gang (the
        # sentinel domain is single-sourced in solver/gangs.py)
        enabled = (m0 > 0) & (gang_j == GANG_FREE) & (tier_j > 0)
        ok_node = (
            (state.kind == 1)
            & c.exist_taint_ok
            & _class_slot_compatible(state, c, statics)
        )
        elig = ev.valid & (~evicted) & (ev.tier < tier_j)  # [N, P]
        avail = state.capacity - state.requests + bonus  # [N, R]
        kfit, cost0 = jax.vmap(
            _node_prefix_fit, in_axes=(0, 0, 0, 0, None)
        )(avail, elig, ev.req, ev.cost, c.requests)  # [N, P+1] each
        kfit = jnp.where(ok_node[:, None] & enabled, kfit, 0)

        def node_round(rc, _):
            evicted_r, bonus_r, m_r, take_r, used_r = rc
            t_full = jnp.where(used_r, 0, jnp.minimum(kfit[:, P], m_r))
            # minimal prefix reaching the node's target take (kfit is
            # monotone in j, so the count of prefixes below target IS the
            # minimal index)
            jneed = jnp.clip(
                jnp.sum((kfit < t_full[:, None]).astype(jnp.int32), axis=1),
                0, P,
            )
            costn = jnp.take_along_axis(cost0, jneed[:, None], axis=1)[:, 0]
            score = jnp.where(
                t_full > 0, costn / t_full.astype(jnp.float32), jnp.inf
            )
            n_star = jnp.argmin(score)
            t = t_full[n_star]
            act = t > 0
            jn = jneed[n_star]
            # jneed indexes the PHYSICAL prefix (freed cumsums run over the
            # padded pod axis with ineligible rows contributing zero), so
            # the evicted set is the eligible pods inside that prefix
            newly = elig[n_star] & (jnp.arange(P) < jn) & act
            evicted_r = evicted_r.at[n_star].set(evicted_r[n_star] | newly)
            freed_n = jnp.sum(
                jnp.where(newly[:, None], ev.req[n_star], 0.0), axis=0
            )
            delta = jnp.where(
                act, freed_n - t.astype(jnp.float32) * c.requests, 0.0
            )
            bonus_r = bonus_r.at[n_star].add(delta)
            take_r = take_r.at[n_star].add(jnp.where(act, t, 0))
            used_r = used_r.at[n_star].set(used_r[n_star] | act)
            return (evicted_r, bonus_r, m_r - jnp.where(act, t, 0),
                    take_r, used_r), None

        init = (
            evicted, bonus, m0,
            jnp.zeros((N,), jnp.int32), jnp.zeros((N,), bool),
        )
        (evicted2, bonus2, m2, take, _used), _ = jax.lax.scan(
            node_round, init, None, length=node_rounds
        )
        return (evicted2, bonus2), (take, m2)

    R = state.requests.shape[1]
    init = (
        jnp.zeros((N, P), dtype=bool),
        jnp.zeros((N, R), dtype=jnp.float32),
    )
    (evicted_f, _bonus), (extra_takes, m_left) = jax.lax.scan(
        class_step, init, (classes, step_tier, step_gang, unplaced)
    )
    return extra_takes, m_left, evicted_f


# graftlint: disable=GL103 -- deliberately non-donating: the input is the
# FINAL SlotState of the main scan, which decode still fetches (template /
# head scalars) after the preemption pass prices the evictions against it
preempt_pass = partial(
    jax.jit, static_argnames=("node_rounds",)
)(_preempt_impl)


def _preempt_batched_impl(state, classes, statics, step_tier, step_gang,
                          unplaced, ev, node_rounds: int):
    return jax.vmap(
        lambda s, c, st, t, g, u, e: _preempt_impl(
            s, c, st, t, g, u, e, node_rounds
        )
    )(state, classes, statics, step_tier, step_gang, unplaced, ev)


# graftlint: disable=GL103 -- non-donating twin of preempt_pass: the
# stacked final states are still read by every member's decode fetch
preempt_pass_batched = partial(
    jax.jit, static_argnames=("node_rounds",)
)(_preempt_batched_impl)
