"""Host-side planner lowering topology constraints to device tensors.

The reference evaluates spread/affinity/anti-affinity per pod per node
(topologygroup.go:181-342: nextDomainTopologySpread / nextDomainAffinity /
nextDomainAntiAffinity over per-group domain counters). Here each group
becomes device count state — a per-slot count plane for hostname-keyed
groups (every slot IS a hostname domain) and a count vector over the label
vocab for label-keyed groups — and each class step derives its admissible
domains / per-slot take caps from that state inside the FFD scan
(ops/ffd.py). The planner's job:

* collect the solve's TopologyGroups (own + inverse), split hostname vs
  label-keyed, and build the per-class owner/sel incidence matrices
  (owner = the group CONSTRAINS the class, matching
  topology.go:400-414 _matching_topologies; sel = the group COUNTS the
  class's placements, matching TopologyGroup.counts:121-124);
* decide device eligibility per class — the dominant shapes (zone/hostname
  spread, hostname anti-affinity, zone/hostname affinity) run in-kernel;
  the exotic rest (non-trivial spread node filters, self-selecting
  label-keyed anti-affinity, multiple self-selecting spreads on one key,
  hostPort pods) fall back to the host loop;
* expand each self-selecting label-spread class into one sub-step per
  admissible domain; the kernel water-fills the class's pods across the
  sub-steps' domains from the live counts (the batched equivalent of the
  reference's per-pod min-count domain selection).

Deliberate batching deviations from pod-at-a-time semantics (documented
here, exercised by tests/test_device_topology.py): a class's pods place as
one atomic batch, so "skew holds at each pod's placement instant" becomes
"skew holds at each class boundary"; host-fallback classes place after all
device classes rather than interleaved by size. Both preserve the parity
contract (final-state constraint satisfaction + node-count parity vs the
greedy oracle).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
    TYPE_AFFINITY,
    TYPE_ANTI_AFFINITY,
    TYPE_SPREAD,
    Topology,
    TopologyGroup,
)
from karpenter_core_tpu.solver.snapshot import PodClass

TYPE_CODE = {TYPE_SPREAD: 0, TYPE_ANTI_AFFINITY: 1, TYPE_AFFINITY: 2}

# sentinel "no bound" for min-domains / ranks
NO_MIN_DOMAINS = -1
RANK_NONE = 1 << 30

# topoaware (ISSUE 20): sentinel domain id for slots/templates with no
# rack attribution — the kernel treats them as the farthest level
TOPO_UNKNOWN = -1


def _trivial_node_filter(group: TopologyGroup) -> bool:
    return all(len(alt) == 0 for alt in group.node_filter.alternatives)


class GangZoneGroup:
    """Synthetic zone-keyed affinity group (gangsched, ISSUE 10): every
    member of a same-zone pod group co-locates in ONE topology zone.

    Duck-types the TopologyGroup surface finalize_arrays consults (key /
    domains / max_skew / min_domains / selects / is_owned_by) and lowers to
    the kernel's existing type-2 (affinity) count state: the first member
    class bootstraps on the first name-ranked admissible zone, pinning its
    slots' zone row to that single value; every later member then sees
    exactly one count>0 domain. No new kernel code — the co-location term
    IS an extra mask tensor over the zone vocab, by construction."""

    type = TYPE_AFFINITY
    max_skew = 1 << 30  # affinity ignores skew
    min_domains = None
    key = apilabels.LABEL_TOPOLOGY_ZONE

    def __init__(self, gang_name: str, member_uids, zone_domains):
        from karpenter_core_tpu.solver.gangs import pod_gang_sig

        self._sig = pod_gang_sig
        self.gang_name = gang_name
        self._uids = frozenset(member_uids)
        self.domains = {z: 0 for z in sorted(zone_domains)}
        self.empty_domains = set(self.domains)

    def selects(self, pod) -> bool:
        g = self._sig(pod)
        return g is not None and g[0] == self.gang_name

    def is_owned_by(self, uid) -> bool:
        return uid in self._uids


def _gang_zone_groups(classes: List[PodClass], topo: Topology) -> list:
    """One GangZoneGroup per same-zone gang present in the class list.
    Requires a non-empty zone domain universe (no zones → nothing to
    co-locate in; the gang simply packs without the synthetic term)."""
    zones = topo.domains.get(apilabels.LABEL_TOPOLOGY_ZONE, ())
    if not zones:
        return []
    # same_zone ORs across members (solver/gangs.collect_gangs contract):
    # every class of a flagged gang joins the group, or an unflagged
    # member would be counted (selects matches by name) yet never pinned
    flagged = {
        g[0]
        for cls in classes
        if (g := getattr(cls, "gang", None)) is not None and g[2]
    }
    by_name: Dict[str, List] = {}
    for cls in classes:
        g = getattr(cls, "gang", None)
        if g is not None and g[0] in flagged:
            by_name.setdefault(g[0], []).extend(p.uid for p in cls.pods)
    return [
        GangZoneGroup(name, uids, zones)
        for name, uids in sorted(by_name.items())
    ]


@dataclass
class DeviceGroup:
    """One topology group lowered to device state."""

    group: TopologyGroup
    inverse: bool  # lives in topo.inverse_topologies
    type_code: int  # 0 spread / 1 anti / 2 affinity
    key: str


@dataclass
class StepSpec:
    """One scan step: a class, optionally pinned to a water-fill domain."""

    class_idx: int  # index into the device class list
    sub_value: int = -1  # vocab value id of the pinned domain (-1: none)
    sub_first: bool = True
    sub_last: bool = True
    wf_group: int = -1  # label-group index driving the water-fill
    wf_key: int = -1  # vocab key id of that group
    zone_rest: Optional[np.ndarray] = None  # [V] bool — this + later domains


@dataclass
class TopoPlan:
    """Planner output. Gh/Gz are >= 1 (padded with a neutral group)."""

    host_groups: List[DeviceGroup]
    label_groups: List[DeviceGroup]
    # groups that cannot be modeled device-side but count device classes;
    # decode re-counts their contributions host-side per (class, slot)
    host_only_groups: List[TopologyGroup]
    device_classes: List[PodClass]
    fallback_classes: List[PodClass]
    fallback_reasons: Dict[int, str]  # id(cls) -> reason
    steps: List[StepSpec]
    # device arrays (filled by finalize_arrays once the vocab is frozen)
    h_type: Optional[np.ndarray] = None  # [Gh] int32
    h_skew: Optional[np.ndarray] = None  # [Gh] int32
    h_sel: Optional[np.ndarray] = None  # [C, Gh] bool
    h_owner: Optional[np.ndarray] = None  # [C, Gh] bool
    z_type: Optional[np.ndarray] = None  # [Gz] int32
    z_skew: Optional[np.ndarray] = None  # [Gz] int32
    z_key: Optional[np.ndarray] = None  # [Gz] int32 vocab key id
    z_mindom: Optional[np.ndarray] = None  # [Gz] int32 (NO_MIN_DOMAINS none)
    z_sel: Optional[np.ndarray] = None  # [C, Gz] bool
    z_owner: Optional[np.ndarray] = None  # [C, Gz] bool
    z_domains: Optional[np.ndarray] = None  # [Gz, V] bool registered universe
    z_rank: Optional[np.ndarray] = None  # [Gz, V] int32 name-sorted rank
    zcount0: Optional[np.ndarray] = None  # [Gz, V] int32 existing-pod counts

    @property
    def Gh(self) -> int:
        return max(len(self.host_groups), 1)

    @property
    def Gz(self) -> int:
        return max(len(self.label_groups), 1)

    def has_device_topology(self) -> bool:
        return bool(self.host_groups or self.label_groups)


def _class_groups(
    cls: PodClass, topo: Topology
) -> Tuple[List[TopologyGroup], List[TopologyGroup]]:
    """(owned groups, inverse groups that constrain this class). Inverse
    groups constrain pods their selector counts (topology.go:400-414)."""
    rep = cls.pods[0]
    owned = [g for g in topo.topologies.values() if g.is_owned_by(rep.uid)]
    inv = [g for g in topo.inverse_topologies.values() if g.selects(rep)]
    return owned, inv


def _eligibility(
    cls: PodClass, owned: List[TopologyGroup], inv: List[TopologyGroup]
) -> Tuple[bool, str, Optional[TopologyGroup]]:
    """Device-representability of a class's constraints. Returns
    (eligible, reason, water-fill group or None)."""
    rep = cls.pods[0]
    if rep.host_ports:
        return False, "hostPort pod with topology constraints", None
    wf: Optional[TopologyGroup] = None
    label_keys_owned: Set[str] = set()
    for g in owned + inv:
        if g.type == TYPE_SPREAD and not _trivial_node_filter(g):
            return False, f"non-trivial spread node filter on {g.key}", None
        if g.key == apilabels.LABEL_HOSTNAME:
            continue
        self_sel = g.selects(rep)
        if g.type == TYPE_ANTI_AFFINITY and self_sel:
            return False, f"self-selecting label anti-affinity on {g.key}", None
        if g.type == TYPE_SPREAD and self_sel:
            if wf is not None:
                return False, "multiple self-selecting label spreads", None
            if g.key in label_keys_owned:
                return False, f"label spread + other group on {g.key}", None
            wf = g
        elif g.key in ({wf.key} if wf is not None else set()):
            return False, f"label spread + other group on {g.key}", None
        label_keys_owned.add(g.key)
    return True, "", wf


def plan_topology(classes: List[PodClass], topo: Topology) -> TopoPlan:
    """Phase A: group collection + per-class eligibility + step expansion
    skeleton (sub-steps are expanded in finalize_arrays when value ids are
    known). Call before the vocab freeze; feed observe_domains() into it."""
    all_groups: List[DeviceGroup] = []
    for g in topo.topologies.values():
        all_groups.append(DeviceGroup(g, False, TYPE_CODE[g.type], g.key))
    for g in topo.inverse_topologies.values():
        all_groups.append(DeviceGroup(g, True, TYPE_CODE[g.type], g.key))
    # synthetic same-zone gang co-location groups (gangsched, ISSUE 10):
    # lowered as ordinary zone-keyed affinity count state; they live only
    # in the plan (never in topo), so the host fallback path is unaware —
    # the atomicity backstop (solver/gangs.enforce_atomicity) covers the
    # decode-divergence edge where a member re-places host-side
    gang_groups = _gang_zone_groups(classes, topo)
    for g in gang_groups:
        all_groups.append(DeviceGroup(g, False, TYPE_CODE[g.type], g.key))

    # groups whose counting/constraining cannot run device-side at all
    host_only = [
        dg.group
        for dg in all_groups
        if dg.group.type == TYPE_SPREAD and not _trivial_node_filter(dg.group)
    ]
    host_only_ids = {id(g) for g in host_only}
    device_groups = [dg for dg in all_groups if id(dg.group) not in host_only_ids]

    host_groups = [dg for dg in device_groups if dg.key == apilabels.LABEL_HOSTNAME]
    label_groups = [dg for dg in device_groups if dg.key != apilabels.LABEL_HOSTNAME]

    device_classes: List[PodClass] = []
    fallback_classes: List[PodClass] = []
    reasons: Dict[int, str] = {}
    wf_by_class: Dict[int, Optional[TopologyGroup]] = {}
    for cls in classes:
        owned, inv = _class_groups(cls, topo)
        if not owned and not inv:
            device_classes.append(cls)
            wf_by_class[id(cls)] = None
            continue
        if any(id(g) in host_only_ids for g in owned):
            fallback_classes.append(cls)
            reasons[id(cls)] = "owns a host-only (node-filtered) group"
            continue
        ok, reason, wf = _eligibility(cls, owned, inv)
        if (
            ok
            and wf is not None
            and wf.key == apilabels.LABEL_TOPOLOGY_ZONE
            and any(g.selects(cls.pods[0]) for g in gang_groups)
        ):
            # a zone water-fill spread and the synthetic same-zone gang
            # affinity fight over one key row — the same conflict
            # _eligibility rejects for real groups, applied here because
            # synthetic groups bypass the owned/inv collection
            ok, reason = False, "zone spread + same-zone gang on one key"
        if ok:
            device_classes.append(cls)
            wf_by_class[id(cls)] = wf
        else:
            fallback_classes.append(cls)
            reasons[id(cls)] = reason

    # Ordering-inversion guard: fallback classes place AFTER the device
    # scan, but a label-keyed anti-affinity OWNER placed in-kernel with an
    # uncommitted key records every value its slot could take
    # (topology.go:541-542 semantics), blocking selected fallback pods the
    # greedy order schedules first. Pull such owners into the fallback set
    # (to fixpoint — moves can cascade) so the whole interacting set
    # resolves in host order.
    label_anti_groups = [
        g
        for g in list(topo.topologies.values())
        + list(topo.inverse_topologies.values())
        if g.type == TYPE_ANTI_AFFINITY and g.key != apilabels.LABEL_HOSTNAME
    ]
    anti_owned_by_class = {
        id(cls): [
            g for g in label_anti_groups if g.is_owned_by(cls.pods[0].uid)
        ]
        for cls in device_classes
    } if label_anti_groups else {}
    moved = bool(anti_owned_by_class)
    while moved:
        moved = False
        fb_reps = [c.pods[0] for c in fallback_classes]
        if not fb_reps:
            break
        for cls in list(device_classes):
            anti_owned = anti_owned_by_class.get(id(cls), ())
            if any(
                g.selects(fr) for g in anti_owned for fr in fb_reps
            ):
                device_classes.remove(cls)
                fallback_classes.append(cls)
                reasons[id(cls)] = (
                    "label anti-affinity owner interacts with a fallback class"
                )
                wf_by_class.pop(id(cls), None)
                moved = True

    plan = TopoPlan(
        host_groups=host_groups,
        label_groups=label_groups,
        host_only_groups=host_only,
        device_classes=device_classes,
        fallback_classes=fallback_classes,
        fallback_reasons=reasons,
        steps=[],
    )
    plan._wf_by_class = wf_by_class  # type: ignore[attr-defined]
    return plan


def observe_domains(plan: TopoPlan, vocab) -> None:
    """Intern every label-group key + registered domain so the frozen vocab
    covers the closed world of topology domains (provisioner.go:251-283)."""
    for dg in plan.label_groups:
        vocab.key_id(dg.key)
        for domain in dg.group.domains:
            vocab.value_id(dg.key, domain)


def finalize_arrays(plan: TopoPlan, frozen, topo: Topology) -> None:
    """Phase B: lower groups to arrays over the frozen vocab and expand
    water-fill sub-steps. Mutates plan in place."""
    C = len(plan.device_classes)
    Gh, Gz, V = plan.Gh, plan.Gz, frozen.V

    plan.h_type = np.zeros((Gh,), dtype=np.int32)
    plan.h_skew = np.zeros((Gh,), dtype=np.int32)
    plan.h_sel = np.zeros((C, Gh), dtype=bool)
    plan.h_owner = np.zeros((C, Gh), dtype=bool)
    plan.z_type = np.zeros((Gz,), dtype=np.int32)
    plan.z_skew = np.zeros((Gz,), dtype=np.int32)
    plan.z_key = np.zeros((Gz,), dtype=np.int32)
    plan.z_mindom = np.full((Gz,), NO_MIN_DOMAINS, dtype=np.int32)
    plan.z_sel = np.zeros((C, Gz), dtype=bool)
    plan.z_owner = np.zeros((C, Gz), dtype=bool)
    plan.z_domains = np.zeros((Gz, V), dtype=bool)
    plan.z_rank = np.full((Gz, V), RANK_NONE, dtype=np.int32)
    plan.zcount0 = np.zeros((Gz, V), dtype=np.int32)

    for gi, dg in enumerate(plan.host_groups):
        plan.h_type[gi] = dg.type_code
        plan.h_skew[gi] = min(dg.group.max_skew, 1 << 30)
    for gi, dg in enumerate(plan.label_groups):
        g = dg.group
        plan.z_type[gi] = dg.type_code
        plan.z_skew[gi] = min(g.max_skew, 1 << 30)
        kid = frozen.keys[dg.key]
        plan.z_key[gi] = kid
        if g.min_domains is not None:
            plan.z_mindom[gi] = g.min_domains
        vmap = frozen.values[kid]
        for rank, domain in enumerate(sorted(g.domains)):
            vid = vmap.get(domain)
            if vid is None:
                continue  # domain outside the closed world never matters
            plan.z_domains[gi, vid] = True
            plan.z_rank[gi, vid] = rank
            plan.zcount0[gi, vid] = g.domains[domain]

    wf_by_class = plan._wf_by_class  # type: ignore[attr-defined]
    label_index = {id(dg.group): gi for gi, dg in enumerate(plan.label_groups)}

    for ci, cls in enumerate(plan.device_classes):
        rep = cls.pods[0]
        owned, inv = _class_groups(cls, topo)
        owned_ids = {id(g) for g in owned}
        for gi, dg in enumerate(plan.host_groups):
            sel = dg.group.selects(rep)
            if dg.inverse:
                # inverse groups: owners RECORD (sel side), selected pods
                # are CONSTRAINED (owner side) — topology.go:244-269,545-547
                plan.h_sel[ci, gi] = id(dg.group) in owned_ids or (
                    dg.group.is_owned_by(rep.uid)
                )
                plan.h_owner[ci, gi] = sel
            else:
                plan.h_sel[ci, gi] = sel
                plan.h_owner[ci, gi] = id(dg.group) in owned_ids
        for gi, dg in enumerate(plan.label_groups):
            sel = dg.group.selects(rep)
            if dg.inverse:
                plan.z_sel[ci, gi] = dg.group.is_owned_by(rep.uid)
                plan.z_owner[ci, gi] = sel
            else:
                plan.z_sel[ci, gi] = sel
                # the is_owned_by disjunct is identity for real groups
                # (owned_ids was built from it) and the ONLY ownership
                # route for synthetic gang groups, which live outside
                # topo.topologies
                plan.z_owner[ci, gi] = (
                    id(dg.group) in owned_ids
                    or dg.group.is_owned_by(rep.uid)
                )

    # --- step expansion ---------------------------------------------------
    steps: List[StepSpec] = []
    for ci, cls in enumerate(plan.device_classes):
        wf = wf_by_class.get(id(cls))
        if wf is None or id(wf) not in label_index:
            steps.append(StepSpec(class_idx=ci))
            continue
        gi = label_index[id(wf)]
        kid = int(plan.z_key[gi])
        # admissible domains: group universe ∧ the pod's STRICT admissible
        # values for the key (pod_domains in topologygroup.go:181-227)
        strict = cls.strict_requirements.get(wf.key)
        vids = [
            vid
            for vid in np.nonzero(plan.z_domains[gi])[0]
            if strict.has(frozen.value_names[kid][vid])
        ]
        # sorted-name order (the reference's tie-break iteration order)
        vids.sort(key=lambda vid: int(plan.z_rank[gi, vid]))
        if not vids:
            # no admissible domain at all: single unsatisfiable step (the
            # kernel sees an empty domain row and reports all pods unplaced)
            steps.append(
                StepSpec(
                    class_idx=ci,
                    wf_group=gi,
                    wf_key=kid,
                    sub_value=-1,
                    zone_rest=np.zeros((V,), dtype=bool),
                )
            )
            continue
        rest = np.zeros((V,), dtype=bool)
        rest[vids] = True
        for i, vid in enumerate(vids):
            zr = rest.copy()
            steps.append(
                StepSpec(
                    class_idx=ci,
                    sub_value=int(vid),
                    sub_first=(i == 0),
                    sub_last=(i == len(vids) - 1),
                    wf_group=gi,
                    wf_key=kid,
                    zone_rest=zr,
                )
            )
            rest[vid] = False
    plan.steps = steps


# -- the network-topology catalog (topoaware, ISSUE 20) ----------------------
# Rack/ICI-adjacency lowering: the `topology.karpenter.sh/rack` (+ optional
# `…/superpod`) label hierarchy on existing nodes and nodeclaim templates
# becomes (a) a small per-domain-pair hop matrix and (b) per-slot /
# per-template domain ids. models/provisioner._prepare_gangsched picks one
# ANCHOR domain per gang and gathers hop-from-anchor rows as the kernel's
# per-step topo_rank planes (ops/ffd level-grouped fill); ops/relax gets the
# same matrix as a class×template cost plane. The hop METRIC itself is
# solver/gangs.hop_distance — one definition across kernel, verifier, twin
# and bench.


@dataclass
class RackPlan:
    """The lowered rack catalog for one solve's slot axis."""

    # sorted distinct (zone, superpod, rack) triples over attributable
    # existing nodes and templates ("" where a level's label is absent)
    domains: List[Tuple[str, str, str]]
    hop: np.ndarray  # [D, D] int32 pairwise hop distance
    slot_domain: np.ndarray  # [N] int32 domain id, TOPO_UNKNOWN elsewhere
    tmpl_domain: np.ndarray  # [S] int32 domain id per template


def _labels_of_triple(t: Tuple[str, str, str]) -> Dict[str, str]:
    zone, superpod, rack = t
    out: Dict[str, str] = {}
    if zone:
        out[apilabels.LABEL_TOPOLOGY_ZONE] = zone
    if superpod:
        out[apilabels.LABEL_TOPOLOGY_SUPERPOD] = superpod
    if rack:
        out[apilabels.LABEL_TOPOLOGY_RACK] = rack
    return out


def _triple_of_labels(labels) -> Optional[Tuple[str, str, str]]:
    """(zone, superpod, rack) of one label dict, or None when the rack
    label is absent — a node without a rack is unattributable and never
    joins the catalog (soundness over completeness)."""
    labels = labels or {}
    rack = labels.get(apilabels.LABEL_TOPOLOGY_RACK)
    if not rack:
        return None
    return (
        labels.get(apilabels.LABEL_TOPOLOGY_ZONE) or "",
        labels.get(apilabels.LABEL_TOPOLOGY_SUPERPOD) or "",
        rack,
    )


def plan_racks(
    node_labels: List[Dict[str, str]],
    template_labels: List[Dict[str, str]],
    n_slots: int,
) -> Optional[RackPlan]:
    """Lower the rack hierarchy for one solve. ``node_labels`` carries one
    label dict per existing-node slot (slots [0, E)); ``template_labels``
    one per nodeclaim template (single-valued rack/superpod/zone
    requirement values, already resolved by the caller). Returns None when
    NO entity carries a rack label — the topoaware subsystem stays fully
    disengaged and every downstream plane keeps its parity-neutral
    all-zeros default."""
    from karpenter_core_tpu.solver import gangs as gangmod

    triples: List[Tuple[str, str, str]] = []
    seen: Set[Tuple[str, str, str]] = set()
    node_triples = [_triple_of_labels(l) for l in node_labels]
    tmpl_triples = [_triple_of_labels(l) for l in template_labels]
    for t in node_triples + tmpl_triples:
        if t is not None and t not in seen:
            seen.add(t)
            triples.append(t)
    if not triples:
        return None
    triples.sort()
    index = {t: i for i, t in enumerate(triples)}
    D = len(triples)
    hop = np.zeros((D, D), dtype=np.int32)
    for i, a in enumerate(triples):
        la = _labels_of_triple(a)
        for j in range(i + 1, D):
            d = gangmod.hop_distance(la, _labels_of_triple(triples[j]))
            hop[i, j] = hop[j, i] = d
    slot_domain = np.full((n_slots,), TOPO_UNKNOWN, dtype=np.int32)
    for si, t in enumerate(node_triples[:n_slots]):
        if t is not None:
            slot_domain[si] = index[t]
    tmpl_domain = np.array(
        [TOPO_UNKNOWN if t is None else index[t] for t in tmpl_triples],
        dtype=np.int32,
    )
    return RackPlan(
        domains=triples, hop=hop, slot_domain=slot_domain,
        tmpl_domain=tmpl_domain,
    )


def gang_anchors(
    rplan: RackPlan,
    gang_names: List[str],
    gang_sizes: List[int],
) -> Dict[str, int]:
    """One anchor domain per gang: greedily the domain whose NEIGHBORHOOD
    absorbs the gang's demand at the smallest hop radius (capacity proxy:
    one pod per slot), with each gang's demand then debited across that
    neighborhood in hop order — the same nearest-first order the level
    fill consumes slots in — so a later gang sees the headroom an earlier
    gang's spill already claimed and anchors in a different superpod (or
    zone) instead of stacking onto one. Ties break on local headroom,
    then sorted domain order; a catalog with no racked existing slots
    anchors on template domains the same way. Pure heuristic — the hard
    bound is enforced post-hoc (solver/gangs.enforce_distance) and
    re-derived by the verifier, so a bad anchor can cost optimality,
    never correctness."""
    from karpenter_core_tpu.solver.gangs import MAX_HOP_DISTANCE

    D = len(rplan.domains)
    headroom = np.zeros((D,), dtype=np.int64)
    for d in rplan.slot_domain:
        if d >= 0:
            headroom[int(d)] += 1
    tmpl_only = not headroom.any()
    if tmpl_only:
        for d in rplan.tmpl_domain:
            if d >= 0:
                headroom[int(d)] += 1
    out: Dict[str, int] = {}
    for name, size in zip(gang_names, gang_sizes):
        need = max(int(size), 1)
        best, best_key = 0, None
        for a in range(D):
            # hop radius at which this anchor's neighborhood absorbs the
            # demand (nearest-first, stable = sorted domain order within
            # a hop level, mirroring the kernel's level-grouped fill)
            order = np.argsort(rplan.hop[a], kind="stable")
            remaining, radius = need, MAX_HOP_DISTANCE + 1
            for d in order:
                remaining -= int(headroom[int(d)])
                if remaining <= 0:
                    radius = int(rplan.hop[a, int(d)])
                    break
            key = (radius, -int(headroom[a]), a)
            if best_key is None or key < best_key:
                best, best_key = a, key
        out[name] = best
        remaining = need
        for d in np.argsort(rplan.hop[best], kind="stable"):
            take = min(remaining, int(headroom[int(d)]))
            headroom[int(d)] -= take
            remaining -= take
            if remaining <= 0:
                break
    return out


def hop_from_anchor(rplan: RackPlan, anchor: int,
                    max_hop: int) -> np.ndarray:
    """[N] int32 hop distance of every slot's domain from the anchor,
    clipped to max_hop; unattributable slots sit at the ceiling. This row
    IS a gang class's topo_rank plane (ops/ffd): level 0 slots fill
    first, then 1, then 2, …"""
    out = np.full(rplan.slot_domain.shape, max_hop, dtype=np.int32)
    known = rplan.slot_domain >= 0
    out[known] = np.minimum(
        rplan.hop[anchor, rplan.slot_domain[known]], max_hop
    )
    return out


def initial_hcounts(plan: TopoPlan, slot_names: List[str], n_slots: int) -> np.ndarray:
    """[Gh, N] counts seeded from each group's live domain counters for the
    existing-node slots (hostname domain == slot). Hostnames with counts but
    no slot never constrain a slot, and hostname min floats at zero
    (topologygroup.go:235-238), so they are safely dropped."""
    out = np.zeros((plan.Gh, n_slots), dtype=np.int32)
    for gi, dg in enumerate(plan.host_groups):
        domains = dg.group.domains
        for si, name in enumerate(slot_names):
            cnt = domains.get(name)
            if cnt:
                out[gi, si] = cnt
    return out
