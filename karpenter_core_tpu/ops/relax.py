"""relaxsolve: the optimizing convex-relaxation solver backend's kernels.

The FFD scan (ops/ffd.py) inherits the reference's template policy:
``fresh_viability`` picks the FIRST workable template per class
(first-template-wins over the weight/name-ordered pool list). That is the
greedy choice the r05 bench shows costing real nodes — cfg3_topology's
parity_nodes_delta (-30/-80 vs greedy) is evidence an *optimizing*
formulation has headroom the heuristic leaves on the table. CvxCluster
(PAPERS.md) shows granular allocation problems of exactly this pod-class ×
instance-shape decompose into convex relaxations that solve as batched
tensor ops; "Priority Matters" shows constraint-based packing beating
heuristic packers on real node-count/$-cost. This module is that
formulation, sized to the existing device encoding:

* ``relax_viability`` — lower the prepared tensors (class×IT compat,
  template prefilters, offering availability, quantized capacity floors,
  offering prices) to the relaxation's constraint planes: per
  (class, template) feasibility, pods-per-fresh-node, and $-per-pod.
* ``relax_choose`` — the relaxation itself: a fractional assignment
  matrix x[c, s] (class c's pod mass on template s) over the per-class
  simplex ∩ feasibility mask, minimized by jit-compiled projected-gradient
  iterations on device (linear $-cost + a small strongly-convex term so
  the iterates converge to a unique point), with same-node-template gang
  rows held to consensus by an ADMM-style averaging projection each step
  — gang atomicity is a CONSTRAINT of the relaxation, not a special case.
  A rounding pass repairs integrality on device: each class takes its
  argmax template when feasible and falls back to the FFD choice
  otherwise, so the output is always a valid per-class
  (new_template, kstar) override for the unmodified FFD scan.
* ``relax_score`` — the scored-fallback comparator: (unplaced pods,
  fresh nodes, $-cost proxy) of a finished solve's SlotState, so the
  driver keeps the FFD answer whenever rounding loses. Consumes the
  final SlotState — a SlotState jit entry for graftlint GL501 routing.

The integral solution is ALWAYS materialized by the unmodified FFD scan
(ffd_solve/gang_solve with the override riding ClassStep.new_template/
kstar), so every topology, tier, eviction, and gang invariant — and the
unmodified ResultVerifier — hold by construction, and the plain FFD
result remains the anytime answer when the iteration budget or the
request deadline expires (models/provisioner._relax_improve).

Batched twins ride the PR 9 vmap seam: a leading problem axis over every
plane, so compatible relax problems coalesce their assignment dispatches
exactly like their solve dispatches (never with ffd-mode problems — the
mode rides _KernelRequest.shape_key and codec.problem_bucket).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# price sentinel for infeasible (class, template) cells and templates with
# no priced offering; far past any real $/node yet small enough that
# float32 sums over a full slot axis stay finite
BIG_PRICE = jnp.float32(1e12)

# default projected-gradient iteration count: the objective is linear +
# a small quadratic, so the iterates contract geometrically and 32 rounds
# land within rounding distance of the optimum at any realistic C×S
DEFAULT_ITERS = 32

# strong-convexity weight and step size for the projected-gradient loop:
# costs are normalized to [0, 1] before the loop, so these are
# scale-free. mu keeps the fixed point unique (pure linear objectives
# ride the simplex boundary and oscillate under finite steps); eta < 1/mu
# keeps the quadratic term contractive.
_MU = jnp.float32(0.05)
_ETA = jnp.float32(0.5)
# mix weight of the fractional-node term against the $-cost term in the
# objective (both normalized to [0, 1]): $-cost leads, node pressure
# breaks $-ties toward denser packings
_NODE_WEIGHT = jnp.float32(0.5)
# mix weight of the cross-domain-hop term (topoaware, ISSUE 20): a soft
# preference only — below the node term so topology nearness breaks
# $-and-node ties toward network-adjacent templates but never pays an
# extra node for it (the hard max-hops bound is enforced post-hoc by
# solver/gangs.enforce_distance and re-derived by the verifier)
_TOPO_WEIGHT = jnp.float32(0.25)


@jax.jit
def relax_viability(
    class_it,  # [C, T] bool — class × instance-type compat
    tmpl_ok,  # [C, S] bool — class × template compat ∧ taints (∧ gang joint)
    tmpl_it,  # [S, T] bool — template's prefiltered instance types
    class_zmask,  # [C, Z] bool
    class_ctmask,  # [C, CT] bool
    tmpl_zmask,  # [S, Z] bool
    tmpl_ctmask,  # [S, CT] bool
    off_avail,  # [T, Z, CT] bool — offering availability lattice
    it_alloc,  # [T, R] float32 (quantized integer units)
    tmpl_overhead,  # [S, R] float32
    class_requests,  # [C, R] float32
    it_price,  # [T] float32 — min available offering price per IT
    k_cap,  # [C] int32 — topology pods-per-host cap (host-floor classes)
):
    """The relaxation's constraint planes: (viable [C, S] bool,
    k_cs [C, S] int32 — max pods per fresh node via template s, k_node
    [C, S] int32 — topology-EFFECTIVE pods per node, podcost [C, S]
    float32 — min $/pod over the viable instance types).

    ``k_cap`` lowers the hostname-keyed topology constraints into the
    relaxation: a class owning a hostname spread (cap maxSkew) or
    anti-affinity (cap 1) group can never stack more than the cap on one
    node no matter the capacity, so its EFFECTIVE pods-per-node — the
    $/pod denominator and the fractional-node estimate — is
    min(capacity k, cap). Without it the relaxation would route
    host-floor classes onto dense expensive nodes they can never fill
    (capacity-only k lies for them). The returned k_cs stays the
    CAPACITY k: it rides the scan's kstar override, and the scan itself
    enforces the topology caps at placement time.

    Same O(C*S*T) memory discipline and margin-free quantized floor
    arithmetic as ops/masks.fresh_viability (k_cs for the chosen template
    is bit-identical to the kstar fresh_viability would report had that
    template been first), so a rounded override never admits a packing
    the FFD scan's own capacity algebra would reject. The $/pod uses the
    per-IT fleet-min offering price (the zone/capacity-type-conditional
    price is approximated by the IT's cheapest available offering — the
    decode refit picks the truly cheapest IT anyway, and the scored
    fallback bounds any mis-estimate at zero regression)."""
    T = off_avail.shape[0]
    viable_it = tmpl_it[None, :, :] & class_it[:, None, :]  # [C, S, T]
    zjoin = class_zmask[:, None, :] & tmpl_zmask[None, :, :]  # [C, S, Z]
    ctjoin = class_ctmask[:, None, :] & tmpl_ctmask[None, :, :]
    joined = (
        zjoin[:, :, :, None] & ctjoin[:, :, None, :]
    ).astype(jnp.float32)  # [C, S, Z, CT] (Z/CT tiny)
    off_flat = off_avail.astype(jnp.float32).reshape(T, -1)
    off_ok = jnp.einsum(
        "tm,csm->cst", off_flat, joined.reshape(*joined.shape[:2], -1)
    ) > 0
    head = it_alloc[None, :, :] - tmpl_overhead[:, None, :]  # [S, T, R]
    r = class_requests
    safe_r = jnp.where(r > 0, r, 1.0)
    k_min = jnp.full(
        (r.shape[0],) + head.shape[:2], jnp.inf, dtype=jnp.float32
    )  # [C, S, T]
    for ri in range(r.shape[1]):  # static unroll, R is small
        ratio_r = head[None, :, :, ri] / safe_r[:, None, None, ri]
        ratio_r = jnp.where(r[:, None, None, ri] > 0, ratio_r, jnp.inf)
        k_min = jnp.minimum(k_min, ratio_r)
    k_it = jnp.floor(k_min)  # [C, S, T]
    ok = viable_it & off_ok & tmpl_ok[:, :, None] & (k_it >= 1.0)
    k_s = jnp.max(jnp.where(ok, k_it, -1.0), axis=-1)  # [C, S]
    viable = k_s >= 1.0
    k_eff = jnp.minimum(k_it, k_cap.astype(jnp.float32)[:, None, None])
    ppod = jnp.where(
        ok, it_price[None, None, :] / jnp.maximum(k_eff, 1.0), BIG_PRICE
    )
    podcost = jnp.min(ppod, axis=-1)  # [C, S]
    # effective pods-per-node per (class, template) — the fractional-node
    # estimate's denominator (the $/pod already folded the cap in)
    k_node = jnp.max(jnp.where(ok, k_eff, -1.0), axis=-1)  # [C, S]
    return (
        viable,
        jnp.clip(k_s, 0, 2**30).astype(jnp.int32),
        jnp.clip(k_node, 0, 2**30).astype(jnp.int32),
        podcost,
    )


def _project_rows(y, viable):
    """Euclidean projection of each row onto the probability simplex
    restricted to its viable support (sort-based, vectorized over rows;
    S is small). Rows with empty support project to zero — the rounding
    pass hands them back to the FFD choice."""
    S = y.shape[1]
    neg = jnp.float32(-3e30)
    yv = jnp.where(viable, y, neg)
    u = -jnp.sort(-yv, axis=1)  # descending; viable entries sort first
    css = jnp.cumsum(u, axis=1)
    j = jnp.arange(1, S + 1, dtype=jnp.float32)
    cond = (u + (1.0 - css) / j[None, :] > 0) & (u > neg / 2)
    rho = jnp.clip(jnp.sum(cond.astype(jnp.int32), axis=1), 1)
    css_rho = jnp.take_along_axis(css, (rho - 1)[:, None], axis=1)[:, 0]
    tau = (css_rho - 1.0) / rho.astype(jnp.float32)
    x = jnp.clip(y - tau[:, None], 0.0) * viable.astype(y.dtype)
    return jnp.where(jnp.any(viable, axis=1)[:, None], x, 0.0)


def _gang_consensus(x, gang_id, num_gangs: int):
    """Average same-template gang members' rows (projection onto the
    consensus subspace — the ADMM coupling step): members iterate on one
    shared fractional row, so the rounded argmax is identical across the
    gang by construction."""
    if num_gangs == 0:
        return x
    member = gang_id >= 0
    gid = jnp.clip(gang_id, 0)
    sum_g = jax.ops.segment_sum(
        jnp.where(member[:, None], x, 0.0), gid, num_segments=num_gangs
    )
    cnt_g = jax.ops.segment_sum(
        member.astype(jnp.float32), gid, num_segments=num_gangs
    )
    mean = sum_g[gid] / jnp.maximum(cnt_g[gid], 1.0)[:, None]
    return jnp.where(member[:, None], mean, x)


def _relax_choose_impl(
    viable,  # [C, S] bool
    k_cs,  # [C, S] int32 — capacity pods/node (rides the kstar override)
    k_node,  # [C, S] int32 — topology-effective pods/node (the estimate)
    podcost,  # [C, S] float32
    counts,  # [C] float32 — pods per class (0 on pad rows)
    gang_id,  # [C] int32 — same-template gang index, -1 outside any
    base_template,  # [C] int32 — fresh_viability's first-wins choice
    base_kstar,  # [C] int32
    warm_template,  # [C] int32 — prior solve's template choice, -1 = none
    topo_cost=None,  # [C, S] float32 — gang-anchor hop distance, or None
    iters: int = DEFAULT_ITERS,
    num_gangs: int = 0,
):
    vf = viable.astype(jnp.float32)
    nv = jnp.sum(vf, axis=1, keepdims=True)
    uniform = vf / jnp.maximum(nv, 1.0)
    # warm start (incsolve, ISSUE 16): rows carrying a prior solution
    # start at that solution's vertex instead of the simplex center —
    # a slowly-drifting problem's optimum is near last round's, so the
    # contraction has almost no distance to cover and the same iteration
    # budget lands measurably closer. A warm index that is no longer
    # viable (catalog drift) falls back to the uniform start; cold rows
    # (sentinel -1) are untouched, so a no-ledger solve is bit-identical
    # to the pre-warm kernel.
    S = viable.shape[1]
    wt = jnp.clip(warm_template, 0)
    warm_viable = (warm_template >= 0) & jnp.take_along_axis(
        viable, wt[:, None], axis=1
    )[:, 0]
    onehot = jax.nn.one_hot(wt, S, dtype=jnp.float32)
    x0 = jnp.where(warm_viable[:, None], onehot, uniform)
    # linear objective: total fractional $-cost of the assignment. The
    # per-cell coefficient is the class's pod mass times its $/pod via
    # that template; normalized to [0, 1] over the viable support so the
    # step size is scale-free.
    cost = jnp.where(viable, counts[:, None] * podcost, 0.0)
    cost = cost / jnp.maximum(jnp.max(jnp.abs(cost)), 1e-6)
    # fractional-node pressure: counts/k_node estimates the nodes this
    # cell would open; normalized and mixed in so equal-$ choices still
    # strictly prefer fewer nodes (the acceptance's primary axis)
    nodeshare = jnp.where(
        viable,
        counts[:, None] / jnp.maximum(k_node.astype(jnp.float32), 1.0),
        0.0,
    )
    nodeshare = nodeshare / jnp.maximum(jnp.max(nodeshare), 1e-6)
    g = cost + _NODE_WEIGHT * nodeshare
    if topo_cost is not None:
        # topoaware (ISSUE 20): per-(gang class, template) hop distance
        # from the gang's anchor domain, normalized like the other terms.
        # None (the plane is absent unless the provisioner's topoaware
        # prep engaged) traces the exact pre-topo program — the
        # off-by-default parity contract at this layer.
        tc = jnp.where(viable, topo_cost, 0.0)
        tc = tc / jnp.maximum(jnp.max(tc), 1e-6)
        g = g + _TOPO_WEIGHT * tc

    def body(_, x):
        y = x - _ETA * (g + _MU * x)
        y = _gang_consensus(y, gang_id, num_gangs)
        return _project_rows(y, viable)

    x = jax.lax.fori_loop(0, iters, body, x0)
    # rounding repair: argmax over the viable support; classes whose
    # support is empty (or whose mass rounded to zero) keep the FFD
    # choice, so the override is always a valid fresh-node policy
    xm = jnp.where(viable, x, -1.0)
    choice = jnp.argmax(xm, axis=1).astype(jnp.int32)
    top = jnp.take_along_axis(xm, choice[:, None], axis=1)[:, 0]
    has = jnp.any(viable, axis=1) & (top > 0)
    nt = jnp.where(has, choice, base_template)
    ks = jnp.where(
        has,
        jnp.take_along_axis(k_cs, jnp.clip(choice, 0)[:, None], axis=1)[:, 0],
        base_kstar,
    )
    changed = jnp.sum(((nt != base_template) & (counts > 0)).astype(jnp.int32))
    return nt, ks, changed


# Assignment + rounding as ONE device dispatch; iteration count and gang
# count are compile-time (both bucket upstream).
relax_choose = partial(
    jax.jit, static_argnames=("iters", "num_gangs")
)(_relax_choose_impl)


def _relax_choose_batched_impl(
    viable, k_cs, k_node, podcost, counts, gang_id, base_template,
    base_kstar, warm_template, topo_cost=None, iters: int = DEFAULT_ITERS,
    num_gangs: int = 0,
):
    return jax.vmap(
        lambda v, k, kn, p, c, gi, bt, bk, wt, tc: _relax_choose_impl(
            v, k, kn, p, c, gi, bt, bk, wt, tc, iters, num_gangs
        )
    )(viable, k_cs, k_node, podcost, counts, gang_id, base_template,
      base_kstar, warm_template, topo_cost)


# vmapped twin for the PR 9 coalescer: stacked relax problems in one
# shape bucket answer their assignment dispatches together
relax_choose_batched = partial(
    jax.jit, static_argnames=("iters", "num_gangs")
)(_relax_choose_batched_impl)


# graftlint: disable=GL103 -- deliberately non-donating: the scorer reads
# a candidate's FINISHED SlotState that the caller still needs whole — the
# winner's planes flow on to the preemption pass and the decode fetch
@jax.jit
def relax_score(state, tmpl_price, unplaced_bc):
    """Scored-fallback comparator over a FINISHED solve's SlotState:
    (unplaced pods, fresh nodes opened, $-cost proxy of the fresh fleet
    — per-template min node price; the decode refit picks the true
    cheapest IT, so this is a consistent relative ranking). Pad slots are
    masked through the fresh predicate (kind==0 never takes), and pad
    classes carry zero unplaced by construction."""
    fresh = (state.kind == 2) & (state.podcount > 0)
    nodes = jnp.sum(fresh.astype(jnp.int32))
    s = jnp.clip(state.template, 0)
    cost = jnp.sum(jnp.where(fresh, tmpl_price[s], 0.0))
    return jnp.sum(unplaced_bc), nodes, cost
