"""The batched first-fit-decreasing kernel.

The reference's hot loop walks pods one at a time through existing nodes,
in-flight claims, and fresh templates (scheduler.go:208-316). Here the walk
is a ``lax.scan`` over pod *equivalence classes* (solver/snapshot.py), each
step placing a whole class with vectorized arithmetic over all open slots at
once:

* slot feasibility — the evolving claim-requirements state is kept as mask
  planes ([N,K,V] value masks + defines/complement/negative/gt/lt planes)
  and evaluated against the class with the same closed-world algebra as
  ops/masks.compatible;
* capacity — per-slot take counts ``k_max`` are computed per instance type
  as floor((allocatable - requests) / class_request) and maximized over the
  slot's viable-IT mask; existing nodes use their fixed available vector;
* placement — first-fit in slot order via exclusive cumulative sums;
  leftovers open ceil(rem / kstar) identical fresh slots from the class's
  chosen template.

Instance-type narrowing rides a dedicated [N,T] viable mask (so the huge
instance-type value vocabulary never enters the slot planes), and offering
availability is evaluated against the slot's zone/capacity-type masks each
step (the claim-requirements-vs-offering check of nodeclaim.go:252).

Known, deliberate round-1 deviations from pod-at-a-time semantics (parity-
tested in tests/test_device_solver.py): within one class placement is
first-fit in slot order rather than emptiest-first (scheduler.go:277), and
same-shape classes are processed class-by-class rather than interleaved —
both only matter once topology counting lands.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

BIG = jnp.float32(3.4e38)


class SlotState(NamedTuple):
    valmask: jax.Array  # [N, K, V] bool — intersected allowed values
    defines: jax.Array  # [N, K] bool
    complement: jax.Array  # [N, K] bool (AND of contributors)
    negative: jax.Array  # [N, K] bool (AND of contributors)
    gt: jax.Array  # [N, K] int32
    lt: jax.Array  # [N, K] int32
    itmask: jax.Array  # [N, T] bool — viable instance types (new slots)
    requests: jax.Array  # [N, R] float32
    capacity: jax.Array  # [N, R] float32 (existing slots; BIG for new)
    kind: jax.Array  # [N] int8: 0 unused, 1 existing, 2 new
    template: jax.Array  # [N] int32 (new slots; -1 otherwise)
    next_free: jax.Array  # [] int32
    overflow: jax.Array  # [] bool


class ClassStep(NamedTuple):
    """Per-class scanned inputs."""

    mask: jax.Array  # [K, V] bool
    defines: jax.Array  # [K] bool
    concrete: jax.Array  # [K] bool
    negative: jax.Array  # [K] bool
    gt: jax.Array  # [K] int32
    lt: jax.Array  # [K] int32
    count: jax.Array  # [] int32
    requests: jax.Array  # [R] float32
    class_it: jax.Array  # [T] bool — pod-vs-instance-type compat
    tmpl_ok: jax.Array  # [S] bool — compat+taints vs each template
    exist_taint_ok: jax.Array  # [N] bool — tolerates existing slot n's taints
    new_template: jax.Array  # [] int32 — chosen template for fresh nodes (-1 none)
    kstar: jax.Array  # [] int32 — pods per fresh node on the best IT


class FFDStatics(NamedTuple):
    """Solve-constant device arrays."""

    it_alloc: jax.Array  # [T, R]
    off_avail: jax.Array  # [T, Z, CT] bool
    zone_key: jax.Array  # [] int32 — key id of the zone label
    ct_key: jax.Array  # [] int32 — key id of the capacity-type label
    tmpl_mask: jax.Array  # [S, K, V]
    tmpl_defines: jax.Array  # [S, K]
    tmpl_complement: jax.Array  # [S, K]
    tmpl_negative: jax.Array  # [S, K]
    tmpl_gt: jax.Array  # [S, K]
    tmpl_lt: jax.Array  # [S, K]
    tmpl_it: jax.Array  # [S, T] bool
    tmpl_overhead: jax.Array  # [S, R] — daemon overhead requests
    well_known: jax.Array  # [K] bool
    gt_none: jax.Array  # [] int32
    lt_none: jax.Array  # [] int32


def _class_slot_compatible(state: SlotState, c: ClassStep, statics: FFDStatics):
    """Requirements.Compatible(class -> slot) vectorized over slots.

    Mirrors ops/masks.compatible; the custom-label rule applies with
    well-known keys exempt on new slots (nodeclaim.go:80) and no exemption
    on existing nodes (existingnode.go:103)."""
    overlap = jnp.any(state.valmask & c.mask[None, :, :], axis=-1)  # [N, K]
    both = state.defines & c.defines[None, :]
    either_concrete = ~state.complement | c.concrete[None, :]
    crossed = jnp.maximum(state.gt, c.gt[None, :]) >= jnp.minimum(
        state.lt, c.lt[None, :]
    )
    empty = jnp.where(either_concrete, ~overlap, crossed)
    both_negative = state.negative & c.negative[None, :]
    rule2 = both & empty & ~both_negative

    is_new = (state.kind == 2)[:, None]
    allow = statics.well_known[None, :] & is_new
    rule1 = (
        c.defines[None, :]
        & ~c.negative[None, :]
        & ~state.defines
        & ~allow
    )
    return ~jnp.any(rule1 | rule2, axis=-1)  # [N]


def _offering_ok(statics: FFDStatics, joined_valmask):
    """[N, T] — instance type t has an available offering compatible with the
    slot's (zone, capacity-type) masks after the joining class narrows them
    (cloudprovider types.go:256-310 Offerings.Available().HasCompatible)."""
    Z = statics.off_avail.shape[1]
    CT = statics.off_avail.shape[2]
    zmask = jax.lax.dynamic_index_in_dim(
        joined_valmask, statics.zone_key, axis=1, keepdims=False
    )[:, :Z]  # [N, Z]
    ctmask = jax.lax.dynamic_index_in_dim(
        joined_valmask, statics.ct_key, axis=1, keepdims=False
    )[:, :CT]  # [N, CT]
    # any (z, ct): off_avail[t, z, ct] & zmask[n, z] & ctmask[n, ct]
    per_zone = jnp.einsum(
        "tzc,nc->ntz",
        statics.off_avail.astype(jnp.float32),
        ctmask.astype(jnp.float32),
    )
    joint = jnp.einsum("ntz,nz->nt", per_zone, zmask.astype(jnp.float32))
    return joint > 0


# Conservative floor margin: float32 division overestimates exact-boundary
# fits (head = 112.0000076 where float64 says 111.9999...), and every such
# overestimate costs a host-fallback pod at decode. Shaving the margin
# under-places at most one pod per slot at exact boundaries; the leftover
# opens a fresh slot on device instead.
K_MARGIN = 1e-4


def _k_max(state: SlotState, c: ClassStep, statics: FFDStatics, viable_it):
    """Max pods of the class each slot can absorb. [N]"""
    r = c.requests  # [R]
    safe_r = jnp.where(r > 0, r, 1.0)
    # new slots: per viable instance type
    head = (statics.it_alloc[None, :, :] - state.requests[:, None, :]) / safe_r
    head = jnp.where(r[None, None, :] > 0, head, BIG)
    k_it = jnp.floor(jnp.min(head, axis=-1) - K_MARGIN)  # [N, T]
    k_it = jnp.where(viable_it, k_it, -1.0)
    k_new = jnp.max(k_it, axis=-1)  # [N]
    # existing slots: fixed available capacity
    head_e = (state.capacity - state.requests) / safe_r
    head_e = jnp.where(r[None, :] > 0, head_e, BIG)
    k_exist = jnp.floor(jnp.min(head_e, axis=-1) - K_MARGIN)  # [N]
    k = jnp.where(state.kind == 1, k_exist, k_new)
    return jnp.clip(k, 0.0, 2**30).astype(jnp.int32)


def ffd_step(state: SlotState, c: ClassStep, statics: FFDStatics):
    """Place one pod class; returns (state', take [N] int32 + unplaced [])."""
    N = state.kind.shape[0]

    # -- feasibility on open slots ---------------------------------------
    req_ok = _class_slot_compatible(state, c, statics)
    taint_ok = jnp.where(
        state.kind == 1,
        c.exist_taint_ok,
        c.tmpl_ok[jnp.clip(state.template, 0)],
    )
    joined_valmask = state.valmask & jnp.where(
        c.defines[None, :, None], c.mask[None, :, :], True
    )
    off_ok = _offering_ok(statics, joined_valmask)  # [N, T]
    viable_it = state.itmask & c.class_it[None, :] & off_ok
    k_max = _k_max(state, c, statics, viable_it)

    feasible = (
        (state.kind > 0)
        & req_ok
        & taint_ok
        & ((state.kind == 1) | jnp.any(viable_it, axis=-1))
    )
    k_max = jnp.where(feasible, k_max, 0)

    # -- first-fit fill in slot order ------------------------------------
    m = c.count
    before = jnp.cumsum(k_max) - k_max  # exclusive prefix
    take = jnp.clip(m - before, 0, k_max)  # [N]
    rem = m - jnp.sum(take)

    # -- open fresh slots -------------------------------------------------
    has_template = c.new_template >= 0
    kstar = jnp.maximum(c.kstar, 1)
    n_new = jnp.where(
        has_template & (rem > 0), (rem + kstar - 1) // kstar, 0
    )
    idx = jnp.arange(N, dtype=jnp.int32)
    fresh = (idx >= state.next_free) & (idx < state.next_free + n_new)
    take_fresh = jnp.where(
        fresh, jnp.clip(rem - (idx - state.next_free) * kstar, 0, kstar), 0
    )
    overflow = state.overflow | (state.next_free + n_new > N)
    unplaced = jnp.where(has_template, 0, rem)

    s = jnp.clip(c.new_template, 0)
    took = take > 0

    # -- merge class requirement state into slots that took ---------------
    # Invariant (established by the model builder): keys an entity does not
    # define carry NEUTRAL state — all-True valmask, complement=True,
    # negative=True, sentinel bounds — so intersection-on-add is uniform:
    # mask AND, complement AND (~concrete), negative AND, gt max, lt min
    # (requirement.go:155-188 under the closed world).
    upd = (took | fresh)[:, None] & c.defines[None, :]  # [N, K]
    base_valmask = jnp.where(
        fresh[:, None, None], statics.tmpl_mask[s][None, :, :], state.valmask
    )
    base_defines = jnp.where(fresh[:, None], statics.tmpl_defines[s][None, :], state.defines)
    base_complement = jnp.where(
        fresh[:, None], statics.tmpl_complement[s][None, :], state.complement
    )
    base_negative = jnp.where(
        fresh[:, None], statics.tmpl_negative[s][None, :], state.negative
    )
    base_gt = jnp.where(fresh[:, None], statics.tmpl_gt[s][None, :], state.gt)
    base_lt = jnp.where(fresh[:, None], statics.tmpl_lt[s][None, :], state.lt)

    new_valmask = jnp.where(
        upd[:, :, None], base_valmask & c.mask[None, :, :], base_valmask
    )
    new_defines = base_defines | upd
    new_complement = jnp.where(
        upd, base_complement & ~c.concrete[None, :], base_complement
    )
    new_negative = jnp.where(upd, base_negative & c.negative[None, :], base_negative)
    new_gt = jnp.where(upd, jnp.maximum(base_gt, c.gt[None, :]), base_gt)
    new_lt = jnp.where(upd, jnp.minimum(base_lt, c.lt[None, :]), base_lt)

    # -- requests / capacity / itmask -------------------------------------
    take_all = take + take_fresh
    base_requests = jnp.where(
        fresh[:, None], statics.tmpl_overhead[s][None, :], state.requests
    )
    new_requests = base_requests + take_all[:, None].astype(jnp.float32) * c.requests[None, :]

    fits_new = jnp.all(
        new_requests[:, None, :] <= statics.it_alloc[None, :, :], axis=-1
    )  # [N, T]
    base_itmask = jnp.where(
        fresh[:, None], statics.tmpl_it[s][None, :], state.itmask
    )
    joined = took | fresh
    new_itmask = jnp.where(
        joined[:, None],
        base_itmask & c.class_it[None, :] & fits_new & _offering_ok(
            statics, new_valmask
        ),
        base_itmask,
    )

    new_kind = jnp.where(fresh, jnp.int8(2), state.kind)
    new_template = jnp.where(fresh, s, state.template)
    new_capacity = jnp.where(fresh[:, None], BIG, state.capacity)

    state2 = SlotState(
        valmask=new_valmask,
        defines=new_defines,
        complement=new_complement,
        negative=new_negative,
        gt=new_gt,
        lt=new_lt,
        itmask=new_itmask,
        requests=new_requests,
        capacity=new_capacity,
        kind=new_kind,
        template=new_template,
        next_free=state.next_free + n_new,
        overflow=overflow,
    )
    return state2, (take_all, unplaced)


@partial(jax.jit, static_argnames=())
def ffd_solve(state: SlotState, classes: ClassStep, statics: FFDStatics):
    """Scan all classes; returns (final state, takes [C, N], unplaced [C])."""
    final, (takes, unplaced) = jax.lax.scan(
        lambda st, c: ffd_step(st, c, statics), state, classes
    )
    return final, takes, unplaced
