"""The batched first-fit-decreasing kernel, topology-aware.

The reference's hot loop walks pods one at a time through existing nodes,
in-flight claims, and fresh templates (scheduler.go:208-316), consulting
per-group topology domain counters for every pod (topologygroup.go:181-342).
Here the walk is a ``lax.scan`` over pod *equivalence classes*
(solver/snapshot.py), each step placing a whole class with vectorized
arithmetic over all open slots at once:

* slot feasibility — the evolving claim-requirements state is kept as mask
  planes ([N,K,V] value masks + defines/complement/negative/gt/lt planes)
  and evaluated against the class with the same closed-world algebra as
  ops/masks.compatible;
* capacity — per-slot take counts ``k_max`` are computed per instance type
  as floor((allocatable - requests) / class_request) and maximized over the
  slot's viable-IT mask; existing nodes use their fixed available vector;
* topology — per-group count state rides the scan carry: label-keyed groups
  (zone etc.) as count vectors over the value vocab (``zcount``), hostname-
  keyed groups as per-slot count planes (``hcount`` — every slot IS a
  hostname domain). Each step derives admissible-domain masks (spread skew /
  affinity count>0 / anti-affinity empty-domain rules), per-slot take caps,
  and — for self-selecting label spreads — a water-fill quota per pinned
  sub-step, the batched equivalent of the reference's per-pod min-count
  domain selection (topologygroup.go:181-227);
* placement — existing nodes first-fit in slot order via exclusive
  cumulative sums, then in-flight claims by capped water-fill over per-slot
  pod counts; leftovers open ceil(rem / kstar) identical fresh slots from
  the class's chosen template.

Instance-type narrowing rides a dedicated [N,T] viable mask (so the huge
instance-type value vocabulary never enters the slot planes), and offering
availability is evaluated against the slot's zone/capacity-type masks each
step (the claim-requirements-vs-offering check of nodeclaim.go:252).

Placement order mirrors the host policy (place_pod): existing nodes
first-fit in slot order, then in-flight claims emptiest-first — a capped
water-fill over per-slot pod counts (_waterfill_take), the batched
equivalent of ``claims.sort(key=len(pods))`` before every add.

Known, deliberate batching deviations from pod-at-a-time semantics
(parity-tested in tests/test_device_solver.py and
tests/test_device_topology.py): emptiest-first ties break by slot creation
index rather than the host's mutating-list order; same-shape classes are
processed class-by-class rather than interleaved; a class's pods place
atomically, so spread skew holds at class boundaries rather than at every
pod; and non-self-selecting spread placements keep the admissible domain
SET rather than pinning to the per-pod min-count domain, so such pods only
feed other groups' counters once something pins their slot. One deviation
is an outright improvement: hostname-keyed anti-affinity/spread classes
run FIRST (models/provisioner._sorted_classes host-floor-first order), so
the distinct-host floor is established with the minimum slot count and
capacity classes fill those slots — the diverse topology benchmark packs
~25% fewer nodes than the pod-at-a-time oracle.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# a numpy scalar, NOT a jnp array: jnp constants close over device buffers,
# which the Pallas twin (ops/pallas_ffd.py) cannot capture inside a kernel
# body — as a literal it lowers identically in both backends
BIG = np.float32(3.4e38)
BIGI = 1 << 30
RANK_NONE = 1 << 30

# topoaware (ISSUE 20): distinct network-distance levels a slot can sit at
# relative to a gang's anchor domain — solver/gangs.MAX_HOP_DISTANCE + 1
# (same rack 0 / same superpod 1 / same zone 2 / farther-or-unknown 3).
# The existing-node fill groups slots by level: all level-0 capacity fills
# before any level-1 capacity, preserving slot order within a level.
TOPO_LEVELS = 4


class SlotState(NamedTuple):
    # adding a field? classify its slot-axis placement in
    # parallel/mesh.SLOT_STATE_SPECS — graftlint GL502 holds the two
    # field sets in lockstep at edit time
    valmask: jax.Array  # [N, K, V] bool — intersected allowed values
    defines: jax.Array  # [N, K] bool
    complement: jax.Array  # [N, K] bool (AND of contributors)
    negative: jax.Array  # [N, K] bool (AND of contributors)
    gt: jax.Array  # [N, K] int32
    lt: jax.Array  # [N, K] int32
    itmask: jax.Array  # [N, T] bool — viable instance types (new slots)
    requests: jax.Array  # [N, R] float32
    capacity: jax.Array  # [N, R] float32 (existing slots; BIG for new)
    kind: jax.Array  # [N] int8: 0 unused, 1 existing, 2 new
    template: jax.Array  # [N] int32 (new slots; -1 otherwise)
    podcount: jax.Array  # [N] int32 — pods placed per slot (drives the
    # emptiest-first fill over in-flight claims, scheduler.py place_pod)
    next_free: jax.Array  # [] int32
    overflow: jax.Array  # [] bool
    # topology count state
    hcount: jax.Array  # [N, Gh] int32 — hostname-group counts per slot
    zcount: jax.Array  # [Gz, V] int32 — label-group counts per value
    carry: jax.Array  # [] int32 — remaining pods of the current wf class


class ClassStep(NamedTuple):
    """Per-class scanned inputs."""

    mask: jax.Array  # [K, V] bool
    defines: jax.Array  # [K] bool
    concrete: jax.Array  # [K] bool
    negative: jax.Array  # [K] bool
    gt: jax.Array  # [K] int32
    lt: jax.Array  # [K] int32
    count: jax.Array  # [] int32
    requests: jax.Array  # [R] float32
    class_it: jax.Array  # [T] bool — pod-vs-instance-type compat
    tmpl_ok: jax.Array  # [S] bool — compat+taints vs each template
    exist_taint_ok: jax.Array  # [N] bool — tolerates existing slot n's taints
    new_template: jax.Array  # [] int32 — chosen template for fresh nodes (-1 none)
    kstar: jax.Array  # [] int32 — pods per fresh node on the best IT
    # topology
    smask: jax.Array  # [K, V] bool — STRICT admissible values (pod_domains)
    h_sel: jax.Array  # [Gh] bool — hostname groups counting this class
    h_owner: jax.Array  # [Gh] bool — hostname groups constraining it
    z_sel: jax.Array  # [Gz] bool
    z_owner: jax.Array  # [Gz] bool
    sub_value: jax.Array  # [] int32 — water-fill pinned value id (-1 none)
    sub_first: jax.Array  # [] bool
    sub_last: jax.Array  # [] bool
    wf_group: jax.Array  # [] int32 — label-group index for water-fill (-1)
    wf_key: jax.Array  # [] int32 — vocab key id of that group
    zone_rest: jax.Array  # [V] bool — this + later sub-step domains
    # topoaware (ISSUE 20): per-slot network-distance level of each
    # existing slot from this class's gang anchor, in [0, TOPO_LEVELS).
    # None (the default, a leafless pytree) traces the classic first-fit
    # cumsum — byte parity for every pre-PR construction site by identical
    # HLO; an all-zeros plane reduces to the same fill arithmetically.
    # Only kind==1 slots consult it (fresh claims keep the water-fill).
    topo_rank: jax.Array = None  # [N] int32


class FFDStatics(NamedTuple):
    """Solve-constant device arrays."""

    it_alloc: jax.Array  # [T, R]
    off_avail: jax.Array  # [T, Z, CT] bool
    zone_key: jax.Array  # [] int32 — key id of the zone label
    ct_key: jax.Array  # [] int32 — key id of the capacity-type label
    tmpl_mask: jax.Array  # [S, K, V]
    tmpl_defines: jax.Array  # [S, K]
    tmpl_complement: jax.Array  # [S, K]
    tmpl_negative: jax.Array  # [S, K]
    tmpl_gt: jax.Array  # [S, K]
    tmpl_lt: jax.Array  # [S, K]
    tmpl_it: jax.Array  # [S, T] bool
    tmpl_overhead: jax.Array  # [S, R] — daemon overhead requests
    well_known: jax.Array  # [K] bool
    gt_none: jax.Array  # [] int32
    lt_none: jax.Array  # [] int32
    # topology group metadata
    h_type: jax.Array  # [Gh] int32: 0 spread / 1 anti / 2 affinity
    h_skew: jax.Array  # [Gh] int32
    h_possel0: jax.Array  # [Gh] bool — positive count on a non-slot hostname
    z_type: jax.Array  # [Gz] int32
    z_skew: jax.Array  # [Gz] int32
    z_key: jax.Array  # [Gz] int32 — vocab key id per label group
    z_mindom: jax.Array  # [Gz] int32 (-1: no minDomains)
    z_domains: jax.Array  # [Gz, V] bool — registered domain universe
    z_rank: jax.Array  # [Gz, V] int32 — sorted-name rank (RANK_NONE outside)


def _class_slot_compatible(state: SlotState, c, statics: FFDStatics):
    """Requirements.Compatible(class -> slot) vectorized over slots.

    Mirrors ops/masks.compatible; the custom-label rule applies with
    well-known keys exempt on new slots (nodeclaim.go:80) and no exemption
    on existing nodes (existingnode.go:103)."""
    overlap = jnp.any(state.valmask & c.mask[None, :, :], axis=-1)  # [N, K]
    both = state.defines & c.defines[None, :]
    either_concrete = ~state.complement | c.concrete[None, :]
    crossed = jnp.maximum(state.gt, c.gt[None, :]) >= jnp.minimum(
        state.lt, c.lt[None, :]
    )
    empty = jnp.where(either_concrete, ~overlap, crossed)
    both_negative = state.negative & c.negative[None, :]
    rule2 = both & empty & ~both_negative

    is_new = (state.kind == 2)[:, None]
    allow = statics.well_known[None, :] & is_new
    rule1 = (
        c.defines[None, :]
        & ~c.negative[None, :]
        & ~state.defines
        & ~allow
    )
    return ~jnp.any(rule1 | rule2, axis=-1)  # [N]


def _offering_ok(statics: FFDStatics, joined_valmask):
    """[N, T] — instance type t has an available offering compatible with the
    slot's (zone, capacity-type) masks after the joining class narrows them
    (cloudprovider types.go:256-310 Offerings.Available().HasCompatible)."""
    Z = statics.off_avail.shape[1]
    CT = statics.off_avail.shape[2]
    zmask = jax.lax.dynamic_index_in_dim(
        joined_valmask, statics.zone_key, axis=1, keepdims=False
    )[:, :Z]  # [N, Z]
    ctmask = jax.lax.dynamic_index_in_dim(
        joined_valmask, statics.ct_key, axis=1, keepdims=False
    )[:, :CT]  # [N, CT]
    # any (z, ct): off_avail[t, z, ct] & zmask[n, z] & ctmask[n, ct]
    per_zone = jnp.einsum(
        "tzc,nc->ntz",
        statics.off_avail.astype(jnp.float32),
        ctmask.astype(jnp.float32),
    )
    joint = jnp.einsum("ntz,nz->nt", per_zone, zmask.astype(jnp.float32))
    return joint > 0


# No floor margin on the per-slot take counts. Requests and capacities
# reach the device as integer-valued float32 (milli/Mi quantization in
# models/provisioner rvec/rvec_cap), so sums, differences, and divisions of
# these integers are exact below 2^24 and floor((alloc-req)/r) needs no
# guard: a margin here would reject exact-boundary fits the greedy oracle's
# float64 math accepts — one fresh node per shaved fit (the r4 cfg3 parity
# gap). Any residual optimism is repaired by the float64 decode refit.


def _k_max(state: SlotState, c: ClassStep, statics: FFDStatics, viable_it):
    """Max pods of the class each slot can absorb: ([N], per-IT [N, T]).

    The per-IT counts double as the post-take fit check — k_raw[n,t] >=
    take ⇔ the slot's cumulative requests after taking still fit type t
    (same exact integer arithmetic) — so ffd_step's itmask update needs no
    second [N, T, R] reduction."""
    r = c.requests  # [R]
    safe_r = jnp.where(r > 0, r, 1.0)
    # new slots: per viable instance type
    head = (statics.it_alloc[None, :, :] - state.requests[:, None, :]) / safe_r
    head = jnp.where(r[None, None, :] > 0, head, BIG)
    k_raw = jnp.floor(jnp.min(head, axis=-1))  # [N, T]
    k_it = jnp.where(viable_it, k_raw, -1.0)
    k_new = jnp.max(k_it, axis=-1)  # [N]
    # existing slots: fixed available capacity
    head_e = (state.capacity - state.requests) / safe_r
    head_e = jnp.where(r[None, :] > 0, head_e, BIG)
    k_exist = jnp.floor(jnp.min(head_e, axis=-1))  # [N]
    k = jnp.where(state.kind == 1, k_exist, k_new)
    return jnp.clip(k, 0.0, 2**30).astype(jnp.int32), k_raw


# ---------------------------------------------------------------------------
# topology: admissible domains, slot caps, water-fill quota


def _label_admissible(state: SlotState, c: ClassStep, statics: FFDStatics):
    """Lower the class's owned label-group constraints to an effective
    requirement restriction.

    Returns (restr [K, V] bool, topo_defined [K] bool): restr is AND-folded
    into the class's value masks; topo_defined marks keys the topology now
    defines (concrete, non-negative — an In over the admissible set).
    Domain rules per group type (all over the group's registered universe ∧
    the class's strict values for the key — pod_domains):

    * spread: count (+1 if self-selecting) - min <= maxSkew, min over the
      pod-admissible universe with the minDomains zero rule
      (topologygroup.go:181-249);
    * anti-affinity: empty domains only (topologygroup.go:316-342);
    * affinity: count>0 domains; a self-selecting class with none bootstraps
      on the first sorted admissible domain (topologygroup.go:253-300).
    """
    Gz, V = statics.z_domains.shape
    K = c.mask.shape[0]
    smask_g = c.smask[statics.z_key]  # [Gz, V]
    padm = smask_g & statics.z_domains
    counts = state.zcount
    cnt = jnp.where(padm, counts, BIGI)
    minc = jnp.min(cnt, axis=1)  # [Gz]
    supported = jnp.sum(padm, axis=1)
    minc = jnp.where(
        (statics.z_mindom >= 0) & (supported < statics.z_mindom),
        0,
        minc,
    )
    inc = jnp.where(c.z_sel, 1, 0)
    delta = counts + inc[:, None] - minc[:, None]
    adm_spread = padm & (delta <= statics.z_skew[:, None])
    adm_anti = padm & (counts == 0)
    pos = padm & (counts > 0)
    any_pos = jnp.any(pos, axis=1)
    rank = jnp.where(padm, statics.z_rank, RANK_NONE)
    boot = (rank == jnp.min(rank, axis=1, keepdims=True)) & padm
    adm_aff = jnp.where(
        any_pos[:, None],
        pos,
        jnp.where(c.z_sel[:, None], boot, jnp.zeros_like(pos)),
    )
    adm = jnp.where(
        (statics.z_type == 0)[:, None],
        adm_spread,
        jnp.where((statics.z_type == 1)[:, None], adm_anti, adm_aff),
    )

    gidx = jnp.arange(Gz, dtype=jnp.int32)
    owner = c.z_owner & (gidx != c.wf_group)  # wf group handled via the pin
    key_oh = jax.nn.one_hot(statics.z_key, K, dtype=jnp.float32)  # [Gz, K]
    owner_key = key_oh * owner[:, None].astype(jnp.float32)
    viol = jnp.einsum("gk,gv->kv", owner_key, (~adm).astype(jnp.float32)) > 0
    restr = ~viol
    topo_defined = jnp.einsum("gk->k", owner_key) > 0

    # water-fill pin: the sub-step's key row collapses to the pinned value
    has_wf = c.wf_group >= 0
    pin_row = (
        jax.nn.one_hot(jnp.clip(c.sub_value, 0), V, dtype=bool)
        & (c.sub_value >= 0)
    )
    wf_key_oh = jax.nn.one_hot(jnp.clip(c.wf_key, 0), K, dtype=bool) & has_wf
    restr = restr & (~wf_key_oh[:, None] | pin_row[None, :])
    topo_defined = topo_defined | wf_key_oh
    return restr, topo_defined


def _host_caps(state: SlotState, c: ClassStep, statics: FFDStatics):
    """Per-slot take caps from owned hostname-keyed groups.

    Hostname min floats at zero (a fresh node is always creatable,
    topologygroup.go:235-238), so:
    * spread, self-selecting: cap = maxSkew - count; else binary on
      count <= maxSkew;
    * anti-affinity: empty slots only; cap 1 when self-selecting;
    * affinity: count>0 slots; a self-selecting class with no positive
      domain anywhere bootstraps (single-slot placement).

    Returns (slot_cap [N] int32, fresh_cap [] int32, single_slot [] bool).
    """
    counts = state.hcount  # [N, Gh]
    sel = c.h_sel
    owner = c.h_owner
    skew = statics.h_skew
    cap_spread = jnp.where(
        sel[None, :],
        skew[None, :] - counts,
        jnp.where(counts <= skew[None, :], BIGI, 0),
    )
    cap_anti = jnp.where(
        counts == 0, jnp.where(sel, 1, BIGI)[None, :], 0
    )
    pos_any = statics.h_possel0 | jnp.any(counts > 0, axis=0)  # [Gh]
    boot = (~pos_any) & sel & (statics.h_type == 2)
    cap_aff = jnp.where(counts > 0, BIGI, 0)
    cap_aff = jnp.where(boot[None, :], BIGI, cap_aff)
    cap = jnp.where(
        (statics.h_type == 0)[None, :],
        cap_spread,
        jnp.where((statics.h_type == 1)[None, :], cap_anti, cap_aff),
    )
    cap = jnp.where(owner[None, :], cap, BIGI)
    slot_cap = jnp.clip(jnp.min(cap, axis=1), 0)  # [N]

    f_cap_g = jnp.where(
        statics.h_type == 0,
        jnp.where(sel, skew, BIGI),
        jnp.where(
            statics.h_type == 1,
            jnp.where(sel, 1, BIGI),
            jnp.where(boot, BIGI, 0),
        ),
    )
    f_cap_g = jnp.where(owner, f_cap_g, BIGI)
    fresh_cap = jnp.clip(jnp.min(f_cap_g), 0)
    single_slot = jnp.any(boot & owner)
    return slot_cap, fresh_cap, single_slot


# Level-search iterations: the water level is bounded by max(count) + m.
# Both are bounded by the solve's total pod count, so callers that know it
# pass ceil(log2(2*pods)) via ffd_solve(level_iters=...); the default
# covers int32 outright.
LEVEL_ITERS = 32


def _level_fill(count, cap, adm, m, rank=None, iters=LEVEL_ITERS):
    """Water-fill m units over admissible entries with per-entry caps.

    Binary-search the level L with fill = clip(L - count, 0, cap) on
    admissible entries, then hand the remainder one-each to the entries
    sitting exactly at the level, lowest rank first (rank=None ties by
    entry index via a cumsum — O(N), used for the slot axis)."""
    cap = jnp.clip(cap, 0)

    def fill_at(L):
        return jnp.where(adm, jnp.clip(L - count, 0, cap), 0)

    hi0 = jnp.max(jnp.where(adm, count, 0)) + m

    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi + 1) // 2
        ok = jnp.sum(fill_at(mid)) <= m
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid - 1)

    L, _ = jax.lax.fori_loop(0, iters, body, (jnp.int32(0), hi0))
    fill = fill_at(L)
    r = m - jnp.sum(fill)
    elig = adm & (fill < cap) & (count + fill == L)
    if rank is None:
        erank = jnp.cumsum(elig) - elig  # exclusive: ties by entry index
    else:
        rk = jnp.where(elig, rank, RANK_NONE)
        erank = jnp.sum((rk[None, :] < rk[:, None]) & elig[None, :], axis=1)
    return fill + (elig & (erank < r))


def _waterfill_take(count, cap, m, iters=LEVEL_ITERS):
    """Distribute m pods over in-flight slots emptiest-first with per-slot
    caps — the batched equivalent of the host policy's one-at-a-time "sort
    claims by pod count, add to the first that admits" loop (scheduler.py
    place_pod). count/cap/returns are [N] int32."""
    return _level_fill(count, cap, cap > 0, m, iters=iters)


def _wf_quota(state: SlotState, c: ClassStep, statics: FFDStatics, m, iters=LEVEL_ITERS):
    """Water-fill share of the pinned sub-step domain.

    The batched equivalent of the reference's per-pod loop: each pod joins
    the min-count admissible domain (ties by sorted-name order), which for m
    identical pods is exactly a water-fill to level L with the remainder
    going one-each to the lowest-(count, name) domains. Under an unsatisfied
    minDomains the min is pinned at zero and each domain caps at maxSkew
    (topologygroup.go:229-249). Later sub-steps recompute over the remaining
    domains with the carried remainder — jointly identical to one water-fill
    over all domains. Capacity shortfalls in one domain spill forward into
    later sub-steps through the carry."""
    g = jnp.clip(c.wf_group, 0)
    counts = state.zcount[g]  # [V]
    padm = c.zone_rest
    skew = statics.z_skew[g]
    full_adm = c.smask[statics.z_key[g]] & statics.z_domains[g]
    supported = jnp.sum(full_adm)
    mindom = statics.z_mindom[g]
    mindom_unsat = (mindom >= 0) & (supported < mindom)
    cap = jnp.where(mindom_unsat, jnp.clip(skew - counts, 0), BIGI)
    quota = _level_fill(
        counts, cap, padm, m, rank=statics.z_rank[g], iters=iters
    )
    return jnp.where(
        c.sub_value >= 0, quota[jnp.clip(c.sub_value, 0)], 0
    )


# ---------------------------------------------------------------------------


def ffd_step(state: SlotState, c: ClassStep, statics: FFDStatics,
             level_iters: int = LEVEL_ITERS):
    """Place one pod class; returns (state', take [N] int32 + unplaced [])."""
    N = state.kind.shape[0]

    # -- topology: effective class requirements + caps + quota -------------
    restr, topo_defined = _label_admissible(state, c, statics)
    eff_mask = c.mask & restr
    eff_defines = c.defines | topo_defined
    eff_concrete = c.concrete | topo_defined
    eff_negative = c.negative & ~topo_defined
    c_eff = c._replace(
        mask=eff_mask,
        defines=eff_defines,
        concrete=eff_concrete,
        negative=eff_negative,
    )
    slot_cap, fresh_cap, single_slot = _host_caps(state, c, statics)

    is_wf = c.wf_group >= 0
    carry0 = jnp.where(c.sub_first, c.count, state.carry)
    m = jnp.where(
        is_wf, _wf_quota(state, c, statics, carry0, iters=level_iters), c.count
    )

    # -- feasibility on open slots ---------------------------------------
    req_ok = _class_slot_compatible(state, c_eff, statics)
    taint_ok = jnp.where(
        state.kind == 1,
        c.exist_taint_ok,
        c.tmpl_ok[jnp.clip(state.template, 0)],
    )
    joined_valmask = state.valmask & jnp.where(
        eff_defines[None, :, None], eff_mask[None, :, :], True
    )
    off_ok = _offering_ok(statics, joined_valmask)  # [N, T]
    viable_it = state.itmask & c.class_it[None, :] & off_ok
    k_max, k_raw = _k_max(state, c, statics, viable_it)

    safe_r_step = jnp.where(c.requests > 0, c.requests, 1.0)
    feasible = (
        (state.kind > 0)
        & req_ok
        & taint_ok
        & ((state.kind == 1) | jnp.any(viable_it, axis=-1))
    )
    k_eff = jnp.minimum(k_max, slot_cap)
    k_eff = jnp.where(feasible, k_eff, 0)

    # -- two-phase fill: existing nodes first-fit in slot order, then
    # in-flight claims emptiest-first (place_pod: existing loop, then
    # claims.sort(key=len(pods))) --------------------------------------
    k_exist_eff = jnp.where(state.kind == 1, k_eff, 0)
    if c.topo_rank is None:
        before = jnp.cumsum(k_exist_eff) - k_exist_eff  # exclusive prefix
    else:
        # level-grouped first-fit (topoaware, ISSUE 20): all capacity at
        # network level 0 fills before any at level 1, slot order within a
        # level. Integer-exact: an all-zero plane puts every slot in level
        # 0, where below=0 and the within-level cumsum IS the classic
        # exclusive prefix — bit-identical fills, the parity the
        # off-by-default contract rides on.
        lvl = jnp.clip(c.topo_rank, 0, TOPO_LEVELS - 1)  # [N]
        onehot = (
            lvl[:, None]
            == jnp.arange(TOPO_LEVELS, dtype=lvl.dtype)[None, :]
        )  # [N, L]
        k_lvl = jnp.where(onehot, k_exist_eff[:, None], 0)  # [N, L]
        lvl_tot = jnp.sum(k_lvl, axis=0)  # [L]
        below = jnp.cumsum(lvl_tot) - lvl_tot  # exclusive over levels
        within = jnp.cumsum(k_lvl, axis=0) - k_lvl  # exclusive in level
        before = below[lvl] + jnp.sum(
            jnp.where(onehot, within, 0), axis=1
        )
    take_exist = jnp.clip(m - before, 0, k_exist_eff)  # [N]
    rem_claims = m - jnp.sum(take_exist)
    k_claim_eff = jnp.where(state.kind == 2, k_eff, 0)
    take_claims = _waterfill_take(
        state.podcount, k_claim_eff, rem_claims, iters=level_iters
    )
    take_normal = take_exist + take_claims
    first_feasible = feasible & (jnp.cumsum(feasible) == 1)
    take_single = jnp.where(first_feasible, jnp.minimum(k_eff, m), 0)
    take = jnp.where(single_slot, take_single, take_normal)
    rem = m - jnp.sum(take)

    # -- open fresh slots -------------------------------------------------
    has_template = (c.new_template >= 0) & (fresh_cap > 0)
    kstar = jnp.clip(jnp.minimum(jnp.maximum(c.kstar, 1), fresh_cap), 1)
    n_new = jnp.where(
        has_template & (rem > 0), (rem + kstar - 1) // kstar, 0
    )
    # affinity bootstrap places on exactly one slot — a fresh one only when
    # no existing slot admitted anything (nextDomainAffinity bootstrap path)
    n_new = jnp.where(
        single_slot, jnp.where(jnp.sum(take) > 0, 0, jnp.minimum(n_new, 1)), n_new
    )
    idx = jnp.arange(N, dtype=jnp.int32)
    fresh = (idx >= state.next_free) & (idx < state.next_free + n_new)
    take_fresh = jnp.where(
        fresh, jnp.clip(rem - (idx - state.next_free) * kstar, 0, kstar), 0
    )
    overflow = state.overflow | (state.next_free + n_new > N)
    unplaced_step = rem - jnp.sum(take_fresh)

    s = jnp.clip(c.new_template, 0)
    took = take > 0

    # -- merge class requirement state into slots that took ---------------
    # Invariant (established by the model builder): keys an entity does not
    # define carry NEUTRAL state — all-True valmask, complement=True,
    # negative=True, sentinel bounds — so intersection-on-add is uniform:
    # mask AND, complement AND (~concrete), negative AND, gt max, lt min
    # (requirement.go:155-188 under the closed world).
    upd = (took | fresh)[:, None] & eff_defines[None, :]  # [N, K]
    base_valmask = jnp.where(
        fresh[:, None, None], statics.tmpl_mask[s][None, :, :], state.valmask
    )
    base_defines = jnp.where(fresh[:, None], statics.tmpl_defines[s][None, :], state.defines)
    base_complement = jnp.where(
        fresh[:, None], statics.tmpl_complement[s][None, :], state.complement
    )
    base_negative = jnp.where(
        fresh[:, None], statics.tmpl_negative[s][None, :], state.negative
    )
    base_gt = jnp.where(fresh[:, None], statics.tmpl_gt[s][None, :], state.gt)
    base_lt = jnp.where(fresh[:, None], statics.tmpl_lt[s][None, :], state.lt)

    new_valmask = jnp.where(
        upd[:, :, None], base_valmask & eff_mask[None, :, :], base_valmask
    )
    new_defines = base_defines | upd
    new_complement = jnp.where(
        upd, base_complement & ~eff_concrete[None, :], base_complement
    )
    new_negative = jnp.where(upd, base_negative & eff_negative[None, :], base_negative)
    new_gt = jnp.where(upd, jnp.maximum(base_gt, c.gt[None, :]), base_gt)
    new_lt = jnp.where(upd, jnp.minimum(base_lt, c.lt[None, :]), base_lt)

    # -- requests / capacity / itmask -------------------------------------
    take_all = take + take_fresh
    base_requests = jnp.where(
        fresh[:, None], statics.tmpl_overhead[s][None, :], state.requests
    )
    new_requests = base_requests + take_all[:, None].astype(jnp.float32) * c.requests[None, :]

    base_itmask = jnp.where(
        fresh[:, None], statics.tmpl_it[s][None, :], state.itmask
    )
    joined = took | fresh
    # post-take viability without re-reducing [N, T, R]:
    # * capacity, open slots: k_raw >= take (see _k_max; state.requests
    #   already carries any overhead, and a dim only grows when a class
    #   requests it — which that class's own k check covers).
    # * capacity, fresh slots: one [T] row with the template overhead on
    #   EVERY dim — including dims the class doesn't request, where the
    #   overhead alone can exceed an instance type's allocatable.
    # * offerings: an OPEN slot's post-take valmask IS joined_valmask, so
    #   the pre-take off_ok is exact; FRESH slots all share one
    #   template∧class zone/ct row — a single [T] evaluation.
    oh = statics.tmpl_overhead[s]  # [R]
    head_f = (statics.it_alloc - oh[None, :]) / safe_r_step[None, :]
    head_f = jnp.where(
        c.requests[None, :] > 0,
        head_f,
        jnp.where(statics.it_alloc >= oh[None, :], BIG, -1.0),
    )
    k_fresh = jnp.floor(jnp.min(head_f, axis=-1))  # [T]
    off_fresh = _offering_ok(
        statics, (statics.tmpl_mask[s] & eff_mask)[None, :, :]
    )[0]  # [T]
    fit_ok = jnp.where(
        fresh[:, None],
        k_fresh[None, :] >= take_all[:, None].astype(k_raw.dtype),
        k_raw >= take_all[:, None].astype(k_raw.dtype),
    )
    off_sel = jnp.where(fresh[:, None], off_fresh[None, :], off_ok)
    new_itmask = jnp.where(
        joined[:, None],
        base_itmask & c.class_it[None, :] & fit_ok & off_sel,
        base_itmask,
    )

    new_kind = jnp.where(fresh, jnp.int8(2), state.kind)
    new_template = jnp.where(fresh, s, state.template)
    new_capacity = jnp.where(fresh[:, None], BIG, state.capacity)

    # -- topology count updates -------------------------------------------
    # hostname groups: every placed pod this group counts lands on exactly
    # its slot's hostname domain
    new_hcount = state.hcount + take_all[:, None] * c.h_sel[None, :].astype(
        jnp.int32
    )
    # label groups: spread/affinity record a placement only once the slot's
    # key row is pinned to a single concrete value (topology.go:543-544);
    # anti-affinity records every value the slot could take (:541-542)
    def_c = new_defines & ~new_complement  # [N, K] concrete-defined
    rowcount = jnp.sum(new_valmask, axis=2)  # [N, K]
    w_pin = (take_all[:, None] * (def_c & (rowcount == 1))).astype(jnp.float32)
    w_anti = (take_all[:, None] * def_c).astype(jnp.float32)
    delta_pin = jnp.einsum("nk,nkv->kv", w_pin, new_valmask.astype(jnp.float32))
    delta_anti = jnp.einsum("nk,nkv->kv", w_anti, new_valmask.astype(jnp.float32))
    delta_g = jnp.where(
        (statics.z_type == 1)[:, None],
        delta_anti[statics.z_key],
        delta_pin[statics.z_key],
    )  # [Gz, V]
    new_zcount = state.zcount + (
        delta_g * c.z_sel[:, None].astype(jnp.float32)
    ).astype(jnp.int32)

    placed = m - unplaced_step
    carry_after = carry0 - placed
    unplaced = jnp.where(
        is_wf, jnp.where(c.sub_last, carry_after, 0), unplaced_step
    )

    state2 = SlotState(
        valmask=new_valmask,
        defines=new_defines,
        complement=new_complement,
        negative=new_negative,
        gt=new_gt,
        lt=new_lt,
        itmask=new_itmask,
        requests=new_requests,
        capacity=new_capacity,
        kind=new_kind,
        template=new_template,
        podcount=state.podcount + take_all,
        next_free=state.next_free + n_new,
        overflow=overflow,
        hcount=new_hcount,
        zcount=new_zcount,
        carry=carry_after,
    )
    return state2, (take_all, unplaced)


def _ffd_solve_impl(state: SlotState, classes: ClassStep, statics: FFDStatics,
                    level_iters: int = LEVEL_ITERS):
    final, (takes, unplaced) = jax.lax.scan(
        lambda st, c: ffd_step(st, c, statics, level_iters), state, classes
    )
    return final, takes, unplaced


# Scan all classes; returns (final state, takes [J, N], unplaced [J]).
# graftlint: disable=GL103 -- deliberately non-donating: tests, the sharded
# harness, and the consolidation sweep reuse the init SlotState across
# calls; the provisioning hot path uses ffd_solve_donated below instead
ffd_solve = partial(jax.jit, static_argnames=("level_iters",))(
    _ffd_solve_impl
)

# Donating twin for the provisioning hot path: the SlotState carry (the
# [N,K,V] requirement planes, the [N,T] itmask, and the hcount/zcount
# topology count planes) is consumed in place instead of double-buffered,
# cutting HBM churn per solve. Callers MUST pass a freshly device-put
# state — models/provisioner rebuilds init_state per round — which is why
# ffd_solve (tests, sharded harness, consolidation) keeps the non-donating
# signature. Donation SURVIVES sharding: a multi-device caller
# (DeviceScheduler(devices=N)) commits the state pre-sharded over the slot
# mesh (parallel/mesh.py), the jit infers matching in/out shardings from
# the arguments (the scan carries them through unchanged), and XLA aliases
# the per-device buffers shard-for-shard — no donation-dropped warnings.
# Donation is a no-op on CPU; the CPU path aliases ffd_solve so
# the test mesh doesn't warn on every compile. The backend probe happens
# lazily at first CALL (we're about to dispatch anyway), never at import —
# importing this module must not initialize the XLA runtime.
_donated_impl = None


def ffd_solve_donated(state: SlotState, classes: ClassStep,
                      statics: FFDStatics, level_iters: int = LEVEL_ITERS):
    global _donated_impl
    if _donated_impl is None:
        if jax.default_backend() != "cpu":
            _donated_impl = partial(
                jax.jit, static_argnames=("level_iters",), donate_argnums=(0,)
            )(_ffd_solve_impl)
        else:
            _donated_impl = ffd_solve
    return _donated_impl(state, classes, statics, level_iters=level_iters)


def _aggregate_takes_impl(takes, unplaced, step_class, num_classes: int):
    tbc = jax.ops.segment_sum(takes, step_class, num_segments=num_classes)
    ubc = jax.ops.segment_sum(unplaced, step_class, num_segments=num_classes)
    return tbc, ubc


@partial(jax.jit, static_argnames=("num_classes",))
def aggregate_takes(takes, unplaced, step_class, num_classes: int):
    """Fuse the per-step scan outputs down to per-CLASS decision planes on
    device: takes_by_class [Cp, N], unplaced_by_class [Cp].

    This is the decode contract's on-device half — the host used to fetch
    the full [J, N] takes matrix (water-fill sub-steps inflate J well past
    the class count) and merge sub-steps per (slot, class) in a Python
    loop; the merge is an exact segment-sum over the step->class index, so
    it runs in one fused dispatch and the fetch shrinks to the class axis.
    Pad steps are inert (zero takes/unplaced), so routing them to segment 0
    is harmless."""
    return _aggregate_takes_impl(takes, unplaced, step_class, num_classes)


# ---------------------------------------------------------------------------
# the problem batch axis (continuous cross-tenant batching, ISSUE 9)
#
# One device dispatch solves B independent problems at once: every leaf of
# SlotState / ClassStep / FFDStatics gains a leading problem axis and the
# whole scan runs under vmap. Compatible problems share their bucketed
# compile shapes by construction (models/provisioner pads every tensor
# axis to power-of-two buckets), so the gateway's coalescer only has to
# find problems in the same shape bucket — per-problem class counts pad to
# the bucket max with inert classes, per-problem slot planes stack. The
# batch axis REPLICATES over the slot mesh (each device holds every
# problem's shard of the slot axis — parallel/mesh.batched_slot_shardings)
# so the vmap composes with the PR 6 pjit-over-slots path unchanged.


def _ffd_solve_batched_impl(state: SlotState, classes: ClassStep,
                            statics: FFDStatics,
                            level_iters: int = LEVEL_ITERS):
    return jax.vmap(
        lambda s, c, st: _ffd_solve_impl(s, c, st, level_iters)
    )(state, classes, statics)


# Batched scan over stacked problems; returns (final states [B, ...],
# takes [B, J, N], unplaced [B, J]).
# graftlint: disable=GL103 -- deliberately non-donating: the batched
# parity tests re-drive the same stacked state, and the production batch
# driver (models/provisioner.solve_batch) uses the donating twin below
ffd_solve_batched = partial(jax.jit, static_argnames=("level_iters",))(
    _ffd_solve_batched_impl
)

# Donating twin for the production batch path, mirroring ffd_solve_donated:
# the stacked [B, ...] SlotState is a per-dispatch copy (jnp.stack of the
# per-problem planes) that can never be reused, so its HBM is donated on a
# real accelerator. CPU aliases the non-donating entry so the virtual test
# mesh doesn't warn per compile; the backend probe is lazy (first call),
# never at import.
_batched_donated_impl = None


def ffd_solve_batched_donated(state: SlotState, classes: ClassStep,
                              statics: FFDStatics,
                              level_iters: int = LEVEL_ITERS):
    global _batched_donated_impl
    if _batched_donated_impl is None:
        if jax.default_backend() != "cpu":
            _batched_donated_impl = partial(
                jax.jit, static_argnames=("level_iters",), donate_argnums=(0,)
            )(_ffd_solve_batched_impl)
        else:
            _batched_donated_impl = ffd_solve_batched
    return _batched_donated_impl(state, classes, statics,
                                 level_iters=level_iters)


@partial(jax.jit, static_argnames=("num_classes",))
def aggregate_takes_batched(takes, unplaced, step_class, num_classes: int):
    """aggregate_takes over a leading problem axis: takes [B, J, N],
    unplaced [B, J], step_class [B, J] (each problem carries its OWN
    step->class index — water-fill sub-step expansion differs per problem
    even at equal padded step counts) -> ([B, Cp, N], [B, Cp])."""
    return jax.vmap(
        lambda t, u, sc: _aggregate_takes_impl(t, u, sc, num_classes)
    )(takes, unplaced, step_class)
