"""Hand-fused Pallas twin of the FFD hot core (``--kernel=pallas``).

``kernel_s`` is ~85% of the primary solve p50 because the ``lax.scan``
over class steps in ops/ffd.py lowers each step's stages — feasibility
masking (``_class_slot_compatible`` / ``_offering_ok`` /
``_label_admissible``), the ``_k_max``/host-cap evaluation, the
exclusive-prefix first-fit scan, and the slot-state update — as separate
XLA ops, re-materializing the [N,K,V] requirement planes and the [N,T]
itmask through HBM between them. This module fuses the whole per-class
inner loop into ONE ``pl.pallas_call`` per class step: every plane is a
whole-array VMEM block, the slot-state inputs alias the slot-state
outputs (``input_output_aliases``) so the carry stays resident in VMEM
across the fused stages instead of round-tripping per op, and the scan
over classes drives the fused kernel exactly like the XLA path drives
``ffd_step``.

Byte parity is by CONSTRUCTION, not by re-derivation: the kernel body
reassembles the SlotState/ClassStep/FFDStatics trees from the refs and
calls the one true ``ops.ffd.ffd_step`` — the same integer-exact float32
arithmetic, the same water-fill, the same prefix scan. The only
transforms at the kernel boundary are losslessly invertible plumbing for
the Mosaic calling convention: bool planes ride as int8 (restored with
``!= 0``) and 0-d scalars ride as (1, 1) blocks (restored by reshape).
The parity battery (tests/test_pallas.py) pins the result wire
byte-identical to the XLA path across every fuzz seed, topology, gang,
relax, batched, and multi-device problem.

CPU story: the backend is probed lazily at first call (never at import —
importing must not initialize the XLA runtime, the ops/ffd contract) and
non-TPU backends run the kernel under ``interpret=True``, so tier-1
exercises the exact fused dataflow — including the aliasing — on the
virtual CPU mesh. Multi-device callers commit their planes REPLICATED
(parallel/mesh.pallas_slot_shardings) before dispatch: the pallas_call
boundary is opaque to the GSPMD partitioner, so the pallas path trades
the sharded-slot-axis throughput of the XLA path for fusion; results are
byte-identical either way, and cross-device throughput is the XLA
backend's job (bench cfg8) while single-core latency is this one's
(bench cfg17).

graftlint: the four jit entries below are registered in
SLOTSTATE_JIT_ENTRIES (GL501/GL503 slot-state placement/gather rules)
and the module sits on the GL604 padding-inertness beat — pad slots
(kind=0) stay inert through the fused step because ffd_step's own
masking runs unchanged inside the kernel.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from karpenter_core_tpu.ops import ffd as ffd_ops
from karpenter_core_tpu.ops.ffd import (
    LEVEL_ITERS,
    ClassStep,
    FFDStatics,
    SlotState,
)

_N_STATE = len(SlotState._fields)


def _interpret() -> bool:
    """Run the kernel interpreted off-TPU (first-call probe, never at
    import)."""
    return jax.default_backend() != "tpu"


def _to_kernel(x, batched: bool):
    """Mosaic-friendly leaf layout: bool -> int8, scalars -> (1, 1)
    blocks ((B, 1) under a leading problem axis). Lossless — the kernel
    body and the wrapper invert it exactly."""
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.int8)
    if x.ndim == (1 if batched else 0):
        x = x.reshape((x.shape[0], 1) if batched else (1, 1))
    return x


def _from_kernel(v, aval):
    """Invert _to_kernel against the original (pre-layout) aval."""
    v = v.reshape(aval.shape)
    if aval.dtype == jnp.bool_:
        return v != 0
    if v.dtype != aval.dtype:
        v = v.astype(aval.dtype)
    return v


def _fused_step(state: SlotState, c: ClassStep, statics: FFDStatics,
                level_iters: int, batched: bool = False):
    """One fused per-class step: a single pallas_call evaluating mask ->
    k_max/caps -> prefix-fit/water-fill -> state update with the slot
    planes held in VMEM. Returns (state', (take_all, unplaced)) with
    ffd_step's exact signature so the scan drivers are interchangeable."""
    operands = (state, c, statics)
    leaves, treedef = jax.tree.flatten(operands)
    avals = [jax.ShapeDtypeStruct(x.shape, x.dtype) for x in leaves]
    kernel_in = [_to_kernel(x, batched) for x in leaves]
    n_in = len(kernel_in)

    if batched:
        B, N = state.kind.shape
        take_aval = jax.ShapeDtypeStruct((B, N), jnp.int32)
        unplaced_shape = (B, 1)
    else:
        N = state.kind.shape[0]
        take_aval = jax.ShapeDtypeStruct((N,), jnp.int32)
        unplaced_shape = (1, 1)
    out_shape = [
        jax.ShapeDtypeStruct(x.shape, x.dtype)
        for x in kernel_in[:_N_STATE]
    ] + [take_aval, jax.ShapeDtypeStruct(unplaced_shape, jnp.int32)]

    def kernel(*refs):
        ins, outs = refs[:n_in], refs[n_in:]
        vals = [
            _from_kernel(r[...], av) for r, av in zip(ins, avals)
        ]
        st, cc, stat = jax.tree.unflatten(treedef, vals)
        if batched:
            st2, (take, unplaced) = jax.vmap(
                lambda s, c_, x: ffd_ops.ffd_step(s, c_, x, level_iters)
            )(st, cc, stat)
        else:
            st2, (take, unplaced) = ffd_ops.ffd_step(
                st, cc, stat, level_iters
            )
        out_vals = list(st2) + [take, unplaced]
        for r, v in zip(outs, out_vals):
            r[...] = _to_kernel(v, batched)

    outs = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        # slot-state carry aliases in place: the planes the scan threads
        # through every class step never leave VMEM between stages
        input_output_aliases={i: i for i in range(_N_STATE)},
        interpret=_interpret(),
    )(*kernel_in)

    state2 = SlotState(
        *(_from_kernel(v, av) for v, av in zip(outs[:_N_STATE], avals))
    )
    take_all = outs[_N_STATE]
    unplaced = outs[_N_STATE + 1].reshape(
        (state.kind.shape[0],) if batched else ()
    )
    return state2, (take_all, unplaced)


def _pallas_ffd_solve_impl(state: SlotState, classes: ClassStep,
                           statics: FFDStatics,
                           level_iters: int = LEVEL_ITERS):
    final, (takes, unplaced) = jax.lax.scan(
        lambda st, c: _fused_step(st, c, statics, level_iters),
        state, classes,
    )
    return final, takes, unplaced


# Fused-scan twin of ops/ffd.ffd_solve; same signature, same returns
# (final state, takes [J, N], unplaced [J]).
# graftlint: disable=GL103 -- deliberately non-donating, mirroring
# ffd_solve: parity tests re-drive the same init SlotState against both
# backends; the provisioning hot path uses pallas_ffd_solve_donated
pallas_ffd_solve = partial(jax.jit, static_argnames=("level_iters",))(
    _pallas_ffd_solve_impl
)

# Donating twin, mirroring ffd_solve_donated byte for byte: the SlotState
# argument's buffers back the aliased kernel carry directly, so the HBM
# the init state arrived in is the HBM the final state leaves in. CPU
# (and any interpreted backend) aliases the non-donating entry so the
# virtual test mesh doesn't warn per compile; the probe is lazy (first
# call), never at import.
_donated_impl = None


def pallas_ffd_solve_donated(state: SlotState, classes: ClassStep,
                             statics: FFDStatics,
                             level_iters: int = LEVEL_ITERS):
    global _donated_impl
    if _donated_impl is None:
        if jax.default_backend() == "tpu":
            _donated_impl = partial(
                jax.jit, static_argnames=("level_iters",),
                donate_argnums=(0,),
            )(_pallas_ffd_solve_impl)
        else:
            _donated_impl = pallas_ffd_solve
    return _donated_impl(state, classes, statics, level_iters=level_iters)


def _pallas_ffd_solve_batched_impl(state: SlotState, classes: ClassStep,
                                   statics: FFDStatics,
                                   level_iters: int = LEVEL_ITERS):
    # The problem axis rides INSIDE the fused kernel (vmap of ffd_step
    # over the leading axis of every block) rather than as a vmap over
    # pallas_call — one kernel invocation per class step regardless of
    # batch size, the same invocation count as the solo path. The scan
    # axis must lead for lax.scan, so the [B, J, ...] class leaves
    # transpose to [J, B, ...] and the outputs transpose back.
    classes_t = jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), classes)
    final, (takes, unplaced) = jax.lax.scan(
        lambda st, c: _fused_step(st, c, statics, level_iters,
                                  batched=True),
        state, classes_t,
    )
    return (
        final,
        jnp.swapaxes(takes, 0, 1),  # [J, B, N] -> [B, J, N]
        jnp.swapaxes(unplaced, 0, 1),  # [J, B] -> [B, J]
    )


# Fused-scan twin of ffd_solve_batched (stacked [B, ...] problems).
# graftlint: disable=GL103 -- deliberately non-donating, mirroring
# ffd_solve_batched: the batched parity tests re-drive the same stacked
# state; production batches use the donating twin below
pallas_ffd_solve_batched = partial(
    jax.jit, static_argnames=("level_iters",)
)(_pallas_ffd_solve_batched_impl)

_batched_donated_impl = None


def pallas_ffd_solve_batched_donated(state: SlotState, classes: ClassStep,
                                     statics: FFDStatics,
                                     level_iters: int = LEVEL_ITERS):
    global _batched_donated_impl
    if _batched_donated_impl is None:
        if jax.default_backend() == "tpu":
            _batched_donated_impl = partial(
                jax.jit, static_argnames=("level_iters",),
                donate_argnums=(0,),
            )(_pallas_ffd_solve_batched_impl)
        else:
            _batched_donated_impl = pallas_ffd_solve_batched
    return _batched_donated_impl(state, classes, statics,
                                 level_iters=level_iters)
