"""Jittable feasibility kernels: the reference's set algebra as tensor ops.

``compatible`` evaluates ``Requirements.Compatible`` (reference:
pkg/scheduling/requirements.go:175-187, 283-304) for every (incoming,
receiver) pair at once. Exactness under the closed world of solver/vocab.py:

* Rule 1 (custom labels): incoming side defines a non-well-known key with a
  positive operator that the receiver doesn't define → incompatible.
  Pure scalar logic on the defines/negative planes.
* Rule 2 (intersects, keys both define): ``intersection.length() == 0`` can
  only happen when (a) at least one side is a concrete (non-complement) set —
  then the intersection is a subset of that side's explicit values, all of
  which are interned in the vocab, so vocab-mask overlap is exact — or
  (b) both sides are complements whose merged Gt/Lt bounds cross
  (requirement.go:163-165); complement∩complement is otherwise a complement
  set with astronomically large cardinality, never empty. Both-negative
  pairs (NotIn/DoesNotExist vs NotIn/DoesNotExist) are exempt
  (requirements.go:288-296).

The per-key overlap is evaluated as a batched matmul over the value axis —
an [N,K*V] × [K*V,M]-shaped contraction batched per key, which XLA tiles
onto the MXU — so feasibility for 50k pod-classes × 800 instance types rides
the systolic array rather than a host loop over set objects.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("custom_rule",))
def compatible(
    inc_mask,
    inc_defines,
    inc_concrete,
    inc_negative,
    inc_gt,
    inc_lt,
    rec_mask,
    rec_defines,
    rec_concrete,
    rec_negative,
    rec_gt,
    rec_lt,
    well_known,
    custom_rule: bool = True,
):
    """Pairwise compatibility.

    incoming entities: [N, K, V] / [N, K] planes (e.g. pod classes)
    receiver entities: [M, K, V] / [M, K] planes (e.g. instance types,
    nodeclaim templates, existing nodes)
    well_known: [K] bool — keys exempt from the custom-label rule.

    Returns ok: [N, M] bool.
    """
    # Per-key overlap count via batched contraction over the value axis:
    # [K, N, V] @ [K, V, M] -> [K, N, M]; bf16 is exact for 0/1 sums up to
    # V <= 256 (integers to 256 are exactly representable).
    a = jnp.transpose(inc_mask, (1, 0, 2)).astype(jnp.bfloat16)
    b = jnp.transpose(rec_mask, (1, 2, 0)).astype(jnp.bfloat16)
    overlap = jax.lax.batch_matmul(a, b) > 0  # [K, N, M]
    overlap = jnp.transpose(overlap, (1, 2, 0))  # [N, M, K]

    both = inc_defines[:, None, :] & rec_defines[None, :, :]  # [N, M, K]
    either_concrete = inc_concrete[:, None, :] | rec_concrete[None, :, :]
    crossed = (
        jnp.maximum(inc_gt[:, None, :], rec_gt[None, :, :])
        >= jnp.minimum(inc_lt[:, None, :], rec_lt[None, :, :])
    )
    empty = jnp.where(either_concrete, ~overlap, crossed)
    both_negative = inc_negative[:, None, :] & rec_negative[None, :, :]
    rule2 = both & empty & ~both_negative

    if custom_rule:
        rule1 = (
            inc_defines[:, None, :]
            & ~inc_negative[:, None, :]
            & ~rec_defines[None, :, :]
            & ~well_known[None, None, :]
        )
        bad = rule1 | rule2
    else:
        bad = rule2
    return ~jnp.any(bad, axis=-1)


@jax.jit
def intersects(
    inc_mask, inc_defines, inc_concrete, inc_negative, inc_gt, inc_lt,
    rec_mask, rec_defines, rec_concrete, rec_negative, rec_gt, rec_lt,
):
    """Pairwise Requirements.Intersects (rule 2 only) — used where the
    reference calls Intersects directly, e.g. instance-type filtering
    (scheduling/nodeclaim.go:296-298) and offering compatibility."""
    return compatible(
        inc_mask, inc_defines, inc_concrete, inc_negative, inc_gt, inc_lt,
        rec_mask, rec_defines, rec_concrete, rec_negative, rec_gt, rec_lt,
        well_known=jnp.zeros(inc_mask.shape[1], dtype=bool),
        custom_rule=False,
    )


@jax.jit
def tolerates(entity_taints, pod_tolerates_taint):
    """Taint feasibility: entity_taints [M, TA] bool (node/template has taint
    ta), pod_tolerates_taint [N, TA] bool (class tolerates taint ta,
    precomputed host-side with Toleration.tolerates). ok[n, m] = every taint
    of m is tolerated by n (reference: pkg/scheduling/taints.go:46-59)."""
    untolerated = entity_taints[None, :, :] & ~pod_tolerates_taint[:, None, :]
    return ~jnp.any(untolerated, axis=-1)


@jax.jit
def fits(requests, allocatable):
    """Resource fit: requests [N, R], allocatable [M, R] →
    ok [N, M] = all-dims requests <= allocatable (reference:
    pkg/utils/resources/resources.go:217-231; negative allocatable never
    fits)."""
    ok = jnp.all(
        requests[:, None, :] <= allocatable[None, :, :], axis=-1
    )
    return ok & jnp.all(allocatable >= 0, axis=-1)[None, :]
