"""Jittable feasibility kernels: the reference's set algebra as tensor ops.

``compatible`` evaluates ``Requirements.Compatible`` (reference:
pkg/scheduling/requirements.go:175-187, 283-304) for every (incoming,
receiver) pair at once. Exactness under the closed world of solver/vocab.py:

* Rule 1 (custom labels): incoming side defines a non-well-known key with a
  positive operator that the receiver doesn't define → incompatible.
  Pure scalar logic on the defines/negative planes.
* Rule 2 (intersects, keys both define): ``intersection.length() == 0`` can
  only happen when (a) at least one side is a concrete (non-complement) set —
  then the intersection is a subset of that side's explicit values, all of
  which are interned in the vocab, so vocab-mask overlap is exact — or
  (b) both sides are complements whose merged Gt/Lt bounds cross
  (requirement.go:163-165); complement∩complement is otherwise a complement
  set with astronomically large cardinality, never empty. Both-negative
  pairs (NotIn/DoesNotExist vs NotIn/DoesNotExist) are exempt
  (requirements.go:288-296).

The per-key overlap is evaluated as a batched matmul over the value axis —
an [N,K*V] × [K*V,M]-shaped contraction batched per key, which XLA tiles
onto the MXU — so feasibility for 50k pod-classes × 800 instance types rides
the systolic array rather than a host loop over set objects.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("custom_rule",))
def compatible(
    inc_mask,
    inc_defines,
    inc_concrete,
    inc_negative,
    inc_gt,
    inc_lt,
    rec_mask,
    rec_defines,
    rec_concrete,
    rec_negative,
    rec_gt,
    rec_lt,
    well_known,
    custom_rule: bool = True,
):
    """Pairwise compatibility.

    incoming entities: [N, K, V] / [N, K] planes (e.g. pod classes)
    receiver entities: [M, K, V] / [M, K] planes (e.g. instance types,
    nodeclaim templates, existing nodes)
    well_known: [K] bool — keys exempt from the custom-label rule.

    Returns ok: [N, M] bool.
    """
    # Per-key overlap count via batched contraction over the value axis:
    # [K, N, V] @ [K, V, M] -> [K, N, M]; bf16 is exact for 0/1 sums up to
    # V <= 256 (integers to 256 are exactly representable).
    a = jnp.transpose(inc_mask, (1, 0, 2)).astype(jnp.bfloat16)
    b = jnp.transpose(rec_mask, (1, 2, 0)).astype(jnp.bfloat16)
    overlap = jax.lax.batch_matmul(a, b) > 0  # [K, N, M]
    overlap = jnp.transpose(overlap, (1, 2, 0))  # [N, M, K]

    both = inc_defines[:, None, :] & rec_defines[None, :, :]  # [N, M, K]
    either_concrete = inc_concrete[:, None, :] | rec_concrete[None, :, :]
    crossed = (
        jnp.maximum(inc_gt[:, None, :], rec_gt[None, :, :])
        >= jnp.minimum(inc_lt[:, None, :], rec_lt[None, :, :])
    )
    empty = jnp.where(either_concrete, ~overlap, crossed)
    both_negative = inc_negative[:, None, :] & rec_negative[None, :, :]
    rule2 = both & empty & ~both_negative

    if custom_rule:
        rule1 = (
            inc_defines[:, None, :]
            & ~inc_negative[:, None, :]
            & ~rec_defines[None, :, :]
            & ~well_known[None, None, :]
        )
        bad = rule1 | rule2
    else:
        bad = rule2
    return ~jnp.any(bad, axis=-1)


@jax.jit
def intersects(
    inc_mask, inc_defines, inc_concrete, inc_negative, inc_gt, inc_lt,
    rec_mask, rec_defines, rec_concrete, rec_negative, rec_gt, rec_lt,
):
    """Pairwise Requirements.Intersects (rule 2 only) — used where the
    reference calls Intersects directly, e.g. instance-type filtering
    (scheduling/nodeclaim.go:296-298) and offering compatibility."""
    return compatible(
        inc_mask, inc_defines, inc_concrete, inc_negative, inc_gt, inc_lt,
        rec_mask, rec_defines, rec_concrete, rec_negative, rec_gt, rec_lt,
        well_known=jnp.zeros(inc_mask.shape[1], dtype=bool),
        custom_rule=False,
    )


@jax.jit
def tolerates(entity_taints, pod_tolerates_taint):
    """Taint feasibility: entity_taints [M, TA] bool (node/template has taint
    ta), pod_tolerates_taint [N, TA] bool (class tolerates taint ta,
    precomputed host-side with Toleration.tolerates). ok[n, m] = every taint
    of m is tolerated by n (reference: pkg/scheduling/taints.go:46-59)."""
    untolerated = entity_taints[None, :, :] & ~pod_tolerates_taint[:, None, :]
    return ~jnp.any(untolerated, axis=-1)


@jax.jit
def fits(requests, allocatable):
    """Resource fit: requests [N, R], allocatable [M, R] →
    ok [N, M] = all-dims requests <= allocatable (reference:
    pkg/utils/resources/resources.go:217-231; negative allocatable never
    fits)."""
    ok = jnp.all(
        requests[:, None, :] <= allocatable[None, :, :], axis=-1
    )
    return ok & jnp.all(allocatable >= 0, axis=-1)[None, :]


@partial(jax.jit, static_argnames=("num_gangs",))
def gang_joint_templates(tmpl_ok, gang_id, num_gangs: int):
    """Same-node-template gang co-location as a mask tensor: AND-reduce
    class×template viability within each gang so every member class sees
    only templates EVERY member could open fresh nodes from — the first
    member's choice then binds the gang by construction (fresh_viability
    is first-template-wins over the joint mask, so members resolve to the
    same template deterministically).

    tmpl_ok: [C, S] bool — per-class template viability (compat ∧ taints)
    gang_id: [C] int32 — index of the class's same-template gang, -1 for
             classes outside any such gang (their rows pass through)
    Returns the narrowed [C, S] mask. Segment-AND rides segment_min over
    int32 (a 0 anywhere in the gang zeroes the template for the gang)."""
    member = gang_id >= 0
    gid = jnp.clip(gang_id, 0)
    ok_i = jnp.where(member[:, None], tmpl_ok.astype(jnp.int32), 1)
    joint_g = jax.ops.segment_min(
        ok_i, gid, num_segments=max(num_gangs, 1)
    )  # [G, S]
    joint = joint_g[gid] > 0
    return jnp.where(member[:, None], tmpl_ok & joint, tmpl_ok)


@jax.jit
def fresh_viability(
    class_it,  # [C, T] bool — class x instance-type compat (intersects)
    tmpl_ok,  # [C, S] bool — class x template compat AND taint tolerance
    tmpl_it,  # [S, T] bool — template's prefiltered instance types
    class_zmask,  # [C, Z] bool — class allowed zones
    class_ctmask,  # [C, CT] bool
    tmpl_zmask,  # [S, Z] bool
    tmpl_ctmask,  # [S, CT] bool
    off_avail,  # [T, Z, CT] bool — offering availability lattice
    it_alloc,  # [T, R] float32 (quantized integer units)
    tmpl_overhead,  # [S, R] float32 — daemon overhead per template
    class_requests,  # [C, R] float32
):
    """Per-class fresh-node viability: the first workable template and the
    max pods per fresh node on its best instance type — the device twin of
    the scheduler's template walk (scheduler.go:288-314 new-claim path +
    nodeclaimtemplate prefilter). Returns (new_template [C] int32, -1 when
    no template works; kstar [C] int32). Runs fully on device so the solve
    needs no host round-trip between the compat kernels and the FFD scan;
    the floor arithmetic matches ops/ffd._k_max exactly (integer-quantized
    float32, margin-free)."""
    # Memory discipline: every intermediate stays O(C*S*T) — the offering
    # lattice contracts through a flattened [T, Z*CT] axis and the resource
    # minimum unrolls over the (small, static) R axis, so large class
    # counts never materialize a [C,S,T,Z] or [C,S,T,R] tensor.
    T = off_avail.shape[0]
    viable = tmpl_it[None, :, :] & class_it[:, None, :]  # [C, S, T]
    zjoin = class_zmask[:, None, :] & tmpl_zmask[None, :, :]  # [C, S, Z]
    ctjoin = class_ctmask[:, None, :] & tmpl_ctmask[None, :, :]  # [C, S, CT]
    joined = (
        zjoin[:, :, :, None] & ctjoin[:, :, None, :]
    ).astype(jnp.float32)  # [C, S, Z, CT] (Z/CT are tiny)
    off_flat = off_avail.astype(jnp.float32).reshape(T, -1)  # [T, Z*CT]
    off_ok = jnp.einsum(
        "tm,csm->cst", off_flat, joined.reshape(*joined.shape[:2], -1)
    ) > 0
    head = it_alloc[None, :, :] - tmpl_overhead[:, None, :]  # [S, T, R]
    r = class_requests  # [C, R]
    safe_r = jnp.where(r > 0, r, 1.0)
    k_min = jnp.full(
        (r.shape[0],) + head.shape[:2], jnp.inf, dtype=jnp.float32
    )  # [C, S, T]
    for ri in range(r.shape[1]):  # static unroll, R is small
        ratio_r = head[None, :, :, ri] / safe_r[:, None, None, ri]
        ratio_r = jnp.where(r[:, None, None, ri] > 0, ratio_r, jnp.inf)
        k_min = jnp.minimum(k_min, ratio_r)
    k_it = jnp.floor(k_min)  # [C, S, T]
    ok = viable & off_ok & tmpl_ok[:, :, None]
    k_s = jnp.max(jnp.where(ok, k_it, -1.0), axis=-1)  # [C, S]
    has = k_s >= 1.0
    any_has = jnp.any(has, axis=1)
    first_s = jnp.argmax(has, axis=1).astype(jnp.int32)
    new_template = jnp.where(any_has, first_s, -1)
    kstar = jnp.where(
        any_has,
        jnp.take_along_axis(k_s, first_s[:, None], axis=1)[:, 0],
        0.0,
    )
    return new_template, jnp.clip(kstar, 0, 2**30).astype(jnp.int32)
