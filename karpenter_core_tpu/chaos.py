"""Seeded chaos harness: deterministic fault injection at the two seams the
control plane talks through — the ``KubeClient`` (apiserver) and the
``CloudProvider``.

The style is solver/remote.py's ``FaultInjector`` (scripted, consumed in
order, exhausted -> healthy) generalized to many fault sites and backed by
a seeded PRNG for rate-driven storms, so a chaos soak is REPRODUCIBLE:
identical seeds draw identical fault sequences, and with a fake clock the
whole run — including the operator's isolation backoffs and the ICE cache's
TTLs — replays event-for-event.

Faults injected BEFORE delegating model "the request never reached the
server" (create/delete/bind/evict): the store is untouched and the caller
retries from clean state. ``update`` faults inject AFTER delegating —
"applied, response lost" — because controllers mutate the store's own
object in place before calling update; raising before the write would
leave a phantom half-state (mutated object, no version bump, no watch
event) that neither a real apiserver nor a real network can produce.

Capacity stockouts are STATE, not a per-call coin flip: an ``IceStorm``
window writes the provider's ``stockouts`` set (the kwok/fake ground
truth), the provider's create raises typed ICE against it, lifecycle marks
the UnavailableOfferings cache, and the re-solve routes around the storm —
the whole availability loop under test.
"""
from __future__ import annotations

import hashlib
import random
import time
from typing import Dict, List, NamedTuple, Optional, Sequence

from karpenter_core_tpu.cloudprovider.types import (
    CloudProviderError,
    CreateError,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    OfferingKey,
)
from karpenter_core_tpu.kube.store import ConflictError, TooManyRequestsError


def fold_seed(seed: int, name: str) -> int:
    """Fold a scenario seed with a stream name into an independent child
    seed. sha256, not hash(): str hashing is salted per process
    (PYTHONHASHSEED), and a fold that moves between runs would void the
    identical-seed→identical-trace contract the twin's fuzzer shrinks
    against."""
    digest = hashlib.sha256(f"{seed}/{name}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


class ChaosSchedule:
    """Deterministic fault source shared by every injector.

    ``script`` maps a seam name to a fault list consumed call-by-call
    (``"ok"`` entries pass through); once a seam's script is exhausted,
    ``rates`` take over: ``{"<seam>.<fault>": probability}`` drawn from a
    PER-SEAM child PRNG (seed folded with the seam name), so the same seed
    replays the same faults AND each seam's fault sequence is independent
    of every other seam's draw count — removing one seam's faults (the
    twin's shrinker dropping a fault class from a failing scenario) leaves
    the remaining seams' sequences untouched, which is what makes
    shrinking monotone instead of a reshuffle."""

    def __init__(
        self,
        seed: int = 0,
        rates: Optional[Dict[str, float]] = None,
        script: Optional[Dict[str, List[str]]] = None,
    ):
        self.seed = seed
        self.rates = dict(rates or {})
        self.script = {k: list(v) for k, v in (script or {}).items()}
        self.draws = 0
        self.seam_draws: Dict[str, int] = {}
        self._seam_rngs: Dict[str, random.Random] = {}

    def _rng(self, seam: str) -> random.Random:
        rng = self._seam_rngs.get(seam)
        if rng is None:
            rng = random.Random(fold_seed(self.seed, seam))
            self._seam_rngs[seam] = rng
        return rng

    def next_fault(self, seam: str, faults: Sequence[str]) -> str:
        self.draws += 1
        self.seam_draws[seam] = self.seam_draws.get(seam, 0) + 1
        queued = self.script.get(seam)
        if queued:
            return queued.pop(0)
        rng = None
        for fault in faults:
            rate = self.rates.get(f"{seam}.{fault}", 0.0)
            if rate:
                # one draw per CONFIGURED fault keeps a seam's sequence a
                # pure function of (seed, seam, its own rate keys): faults
                # of OTHER seams can come and go without shifting it
                if rng is None:
                    rng = self._rng(seam)
                if rng.random() < rate:
                    return fault
        return "ok"


class ChaosKubeClient:
    """KubeClient wrapper injecting apiserver-shaped faults on writes:
    ConflictError (optimistic-lock race), TooManyRequestsError (apiserver
    overload), and latency (a slow round-trip, stepped on a fake clock).
    Reads delegate untouched — the seam under test is write contention."""

    WRITE_FAULTS = ("conflict", "too_many_requests", "latency")

    def __init__(self, inner, schedule: ChaosSchedule, latency: float = 0.25):
        self._inner = inner
        self.schedule = schedule
        self.latency = latency
        self.injected: Dict[str, int] = {}

    def __getattr__(self, name: str):
        return getattr(self._inner, name)

    def _fault(self, seam: str, verb: str, detail: str) -> None:
        fault = self.schedule.next_fault(seam, self.WRITE_FAULTS)
        if fault == "ok":
            return
        self.injected[fault] = self.injected.get(fault, 0) + 1
        if fault == "latency":
            clock = getattr(self._inner, "clock", None)
            if clock is not None and hasattr(clock, "step"):
                clock.step(self.latency)
            else:
                time.sleep(min(self.latency, 0.01))
            return
        if fault == "conflict":
            raise ConflictError(f"chaos: injected conflict on {verb} {detail}")
        if fault == "too_many_requests":
            raise TooManyRequestsError(
                f"chaos: injected 429 on {verb} {detail}"
            )
        raise ValueError(f"unknown chaos fault {fault!r}")

    @staticmethod
    def _detail(obj) -> str:
        return f"{type(obj).__name__}/{obj.metadata.name}"

    # request-lost faults (store untouched, caller retries clean)

    def create(self, obj):
        self._fault("kube.create", "create", self._detail(obj))
        return self._inner.create(obj)

    def delete(self, obj) -> None:
        self._fault("kube.delete", "delete", self._detail(obj))
        self._inner.delete(obj)

    def bind(self, pod, node_name: str) -> None:
        self._fault("kube.bind", "bind", self._detail(pod))
        self._inner.bind(pod, node_name)

    def evict(self, pod) -> None:
        self._fault("kube.evict", "evict", self._detail(pod))
        self._inner.evict(pod)

    # response-lost fault (applied first — see module docstring)

    def update(self, obj):
        out = self._inner.update(obj)
        self._fault("kube.update", "update", self._detail(obj))
        return out


class SolverChaos:
    """Device-tier fault injector for the solverd sidecar (the third chaos
    seam, after the kube client and the cloud provider): installed on a
    ``SolverDaemon`` it perturbs the solve pipeline at the three points
    the robustness layer must survive —

    * ``wedge`` / ``wedge:<s>`` — the device step sleeps past the
      watchdog budget (the wedged-solve shape: the exclusive grant is
      held, the watchdog trips, the process exits crash-only);
    * ``crash`` — the device step raises (the poison-pill shape: the
      client sees a 500, both quarantines count a strike, and N crashes
      route the problem straight to greedy fleet-wide);
    * ``corrupt_wire`` — the encoded result bytes are deterministically
      damaged (truncation + bit flips), exercising the client's decode/
      ``_materialize`` hardening and the quarantine strike path;
    * ``bad_result`` — the Results object is sabotaged BEFORE encoding
      (a pod silently dropped from a claim), producing a structurally
      valid wire whose content fails the client's ResultVerifier.

    Faults draw from the shared seeded ``ChaosSchedule`` (seam
    ``solverd.solve`` by default; a fleet twin names one seam per member,
    e.g. ``solverd.solve.m2``, so murdering one member's faults never
    shifts its siblings' draws), so a soak replays identically per seed."""

    FAULTS = ("wedge", "crash", "corrupt_wire", "bad_result")

    def __init__(
        self,
        schedule: ChaosSchedule,
        wedge_seconds: float = 1.0,
        sleep=time.sleep,
        seam: str = "solverd.solve",
    ):
        self.schedule = schedule
        self.wedge_seconds = wedge_seconds
        self.sleep = sleep
        self.seam = seam
        self.injected: Dict[str, int] = {}

    def next_fault(self) -> str:
        return self.schedule.next_fault(self.seam, self.FAULTS)

    def _count(self, fault: str) -> None:
        self.injected[fault] = self.injected.get(fault, 0) + 1

    def wedge(self, fault: str) -> None:
        """Hold the exclusive device grant well past any sane budget."""
        self._count("wedge")
        seconds = self.wedge_seconds
        if ":" in fault:
            seconds = float(fault.split(":", 1)[1])
        self.sleep(seconds)

    def crash(self) -> None:
        """Blow up the device phase (counted as a poison strike by both
        quarantine sites; the client sees a 500)."""
        self._count("crash")
        raise RuntimeError("chaos: injected device-phase crash")

    def corrupt(self, data: bytes) -> bytes:
        """Deterministic wire damage: drop the tail and flip bytes in the
        middle — enough to defeat both the npz container and any JSON
        inside, without randomness (the soak must replay per seed)."""
        self._count("corrupt_wire")
        if len(data) < 16:
            return b"\x00" * len(data)
        cut = data[: max(len(data) // 2, 8)]
        mid = len(cut) // 2
        return cut[:mid] + bytes(b ^ 0xFF for b in cut[mid:mid + 8]) + cut[mid + 8:]

    def sabotage(self, results) -> None:
        """Make a valid Results lie: silently drop one placed pod (it
        stays out of pod_errors, so pod conservation breaks — the exact
        defect class an optimizing-backend bug would produce)."""
        self._count("bad_result")
        for claim in results.new_node_claims:
            if claim.pods:
                claim.pods.pop()
                return
        for sim in results.existing_nodes:
            if sim.pods:
                sim.pods.pop()
                return


class IceStorm(NamedTuple):
    """A capacity stockout window: ``offerings`` are unfillable during
    [start, start+duration) of the provider's clock."""

    start: float
    duration: float
    offerings: "tuple[OfferingKey, ...]"


class ChaosCloudProvider:
    """CloudProvider wrapper: per-call create/delete/get faults plus
    time-windowed ICE storms written into the inner provider's ground-truth
    ``stockouts`` set (kwok/fake both expose it)."""

    CREATE_FAULTS = ("create_error", "insufficient_capacity")

    def __init__(
        self,
        inner,
        schedule: ChaosSchedule,
        storms: Sequence[IceStorm] = (),
        clock=None,
    ):
        from karpenter_core_tpu.utils.clock import Clock

        self._inner = inner
        self.schedule = schedule
        self.storms = list(storms)
        self.clock = clock or getattr(inner, "clock", None) or Clock()
        self._base_stockouts = set(getattr(inner, "stockouts", set()))
        self.injected: Dict[str, int] = {}

    def __getattr__(self, name: str):
        return getattr(self._inner, name)

    def _count(self, fault: str) -> None:
        self.injected[fault] = self.injected.get(fault, 0) + 1

    def _apply_storms(self) -> None:
        if not self.storms:
            return
        now = self.clock.now()
        active: set = set()
        for storm in self.storms:
            if storm.start <= now < storm.start + storm.duration:
                active.update(OfferingKey(*k) for k in storm.offerings)
        self._inner.stockouts = self._base_stockouts | active

    def create(self, node_claim):
        self._apply_storms()
        fault = self.schedule.next_fault("cloud.create", self.CREATE_FAULTS)
        if fault == "create_error":
            self._count(fault)
            raise CreateError(
                "chaos: injected launch failure",
                condition_reason="ChaosInjected",
            )
        if fault == "insufficient_capacity":
            # context-free ICE (an aggregate stockout the provider could not
            # attribute): lifecycle deletes the claim and the re-solve
            # retries the same offering — the pre-cache degradation path
            self._count(fault)
            raise InsufficientCapacityError(
                "chaos: injected capacity stockout"
            )
        return self._inner.create(node_claim)

    def delete(self, node_claim) -> None:
        if self.schedule.next_fault("cloud.delete", ("delete_error",)) != "ok":
            self._count("delete_error")
            raise CloudProviderError("chaos: injected delete failure")
        self._inner.delete(node_claim)

    def get(self, provider_id: str):
        if self.schedule.next_fault("cloud.get", ("not_found",)) != "ok":
            self._count("not_found")
            raise NodeClaimNotFoundError(
                f"chaos: injected not-found for {provider_id}"
            )
        return self._inner.get(provider_id)
