"""Node auto-repair (feature-gated): force-delete claims whose unhealthy
condition outlasted the provider's toleration, with a 20%-unhealthy
circuit breaker (reference: pkg/controllers/node/health/controller.go:50-222).
"""
from __future__ import annotations

import math

from karpenter_core_tpu.api.objects import Node

UNHEALTHY_THRESHOLD = 0.20  # health/controller.go:188-222


class NodeHealth:
    def __init__(self, kube, cluster, cloud_provider, clock, enabled: bool):
        self.kube = kube
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock
        self.enabled = enabled
        # node conditions carry no transition times in our object model, so
        # the controller tracks first-observed-unhealthy itself (the
        # reference reads condition.LastTransitionTime)
        self._first_seen: dict = {}  # (node name, condition type) -> time

    def reconcile(self, node: Node) -> None:
        if not self.enabled:
            return
        # never repair a node that is already terminating (or, within this
        # pass, already terminated) — the reference skips deleting nodes
        if node.metadata.deletion_timestamp is not None:
            return
        if self.kube.get(Node, node.name) is None:
            return
        policies = self.cloud_provider.repair_policies()
        if not policies:
            return
        # prune windows for nodes other controllers deleted, so a later
        # name-reuse never inherits an expired toleration window
        live = {n.name for n in self.kube.list_nodes()}
        for key in [k for k in self._first_seen if k[0] not in live]:
            del self._first_seen[key]
        hit = self._unhealthy_policy(node, policies)
        if hit is None:
            # healthy: clear any tracked windows for this node
            for key in [k for k in self._first_seen if k[0] == node.name]:
                del self._first_seen[key]
            return
        policy = hit
        key = (node.name, policy.condition_type)
        since = self._first_seen.setdefault(key, self.clock.now())
        if self.clock.since(since) < policy.toleration_duration:
            return
        if self._circuit_broken(policies):
            return
        claims = [
            c
            for c in self.kube.list_nodeclaims()
            if c.status.node_name == node.name
        ]
        for c in claims:
            self.kube.delete(c)
        self.kube.delete(node)
        self._first_seen.pop(key, None)

    def _unhealthy_policy(self, node: Node, policies):
        for policy in policies:
            for cond in node.status.conditions:
                ctype, status = cond[0], cond[1]
                if ctype == policy.condition_type and status == policy.condition_status:
                    return policy
        return None

    def _circuit_broken(self, policies) -> bool:
        """Stop repairs when unhealthy nodes exceed ceil(20%) of the cluster
        — likely systemic, not node-level; the round-up mirrors PDB
        percentage logic so small clusters can still repair one node
        (health/controller.go:188-222)."""
        nodes = self.kube.list_nodes()
        if not nodes:
            return False
        unhealthy = sum(
            1 for n in nodes if self._unhealthy_policy(n, policies) is not None
        )
        threshold = math.ceil(UNHEALTHY_THRESHOLD * len(nodes) - 1e-9)
        return unhealthy > threshold
