"""Node graceful teardown: taint → drain → instance terminated → finalizer
removed (reference: pkg/controllers/node/termination/controller.go:67-176,
terminator/terminator.go:55-165).
"""
from __future__ import annotations

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import Node
from karpenter_core_tpu.cloudprovider.types import NodeClaimNotFoundError
from karpenter_core_tpu.kube.store import (
    ConflictError,
    NotFoundError,
    TooManyRequestsError,
)
from karpenter_core_tpu.scheduling.taints import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_core_tpu.utils import pod as podutil

_CRITICAL_PRIORITY_CLASSES = ("system-cluster-critical", "system-node-critical")

# per-pod eviction retry backoff, the eviction queue's
# ItemExponentialFailureRateLimiter curve (terminator/eviction.go:95,
# orchestration/queue.go:50-54): 1s doubling to a 10s ceiling
EVICT_BACKOFF_BASE = 1.0
EVICT_BACKOFF_CAP = 10.0


def _is_critical(pod) -> bool:
    return pod.priority_class_name in _CRITICAL_PRIORITY_CLASSES


class NodeTermination:
    def __init__(self, kube, cluster, cloud_provider, clock, recorder=None):
        self.kube = kube
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock
        self.recorder = recorder
        # pod key -> (not-before time, current delay); entries drop on
        # success so a repeatedly PDB-blocked (429) pod retries at 1, 2, 4,
        # 8, 10, 10... seconds instead of hammering the apiserver every pass
        self._evict_backoff: dict = {}

    def backoff_wait_remaining(self) -> float:
        """Seconds until the nearest eviction retry unblocks (0 when none);
        lets a fake-clock driver elapse the backoff instead of idling."""
        now = self.clock.now()
        waits = [nb - now for nb, _ in self._evict_backoff.values() if nb > now]
        return min(waits) if waits else 0.0

    def reconcile(self, node: Node) -> None:
        # a stale-resource_version conflict on any of the node/claim writes
        # below is an expected optimistic-lock race (another controller got
        # there first), not a crash: drop this pass and retry against the
        # fresh object next reconcile — the controller-runtime conflict
        # requeue, consistent with the operator's isolation wrapper (which
        # would otherwise count it as a reconcile error and back off)
        try:
            self._reconcile(node)
        except ConflictError:
            return

    def _reconcile(self, node: Node) -> None:
        if node.metadata.deletion_timestamp is None:
            return
        if apilabels.TERMINATION_FINALIZER not in node.metadata.finalizers:
            return
        # bound the backoff map: pods force-deleted mid-backoff (TGP) would
        # otherwise leave entries forever
        if len(self._evict_backoff) > 256:
            live = {p.key() for p in self.kube.list_pods()}
            self._evict_backoff = {
                k: v for k, v in self._evict_backoff.items() if k in live
            }

        # delete owning NodeClaims first (controller.go:178-188)
        claims = [
            c
            for c in self.kube.list_nodeclaims()
            if c.status.provider_id == node.provider_id
        ]
        for c in claims:
            if c.metadata.deletion_timestamp is None:
                self.kube.delete(c)

        # taint so nothing schedules during the drain (terminator.go:55)
        if not any(
            t.key == DISRUPTED_NO_SCHEDULE_TAINT.key for t in node.taints
        ):
            node.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
            self.kube.update(node)

        # TGP enforcement (terminator.go:140-165): a NodeClaim
        # terminationGracePeriod sets a hard node deadline; each pod is
        # force-deleted (bypassing PDBs) at deadline − podGracePeriod so it
        # still gets its full grace window before the node dies
        deadline = self._termination_deadline(node, claims)
        if deadline is not None:
            for p in list(self.cluster.pods_on_node(node.name)):
                if p.is_daemonset or p.is_mirror:
                    continue
                if self.clock.now() >= deadline - p.termination_grace_period_seconds:
                    try:
                        self.kube.delete(p)
                    except NotFoundError:
                        pass

        # drain in priority groups (graceful-node-shutdown order,
        # terminator.go:119-138): non-critical pods evict first; critical
        # pods only once the earlier group is gone. A PDB-blocked eviction
        # (429) leaves the pod for the next reconcile — the drain proceeds
        # at the budget's allowed rate (eviction.go:176)
        evictable = [
            p
            for p in self.cluster.pods_on_node(node.name)
            if podutil.is_evictable(p) and not p.is_daemonset
        ]
        groups = [
            [p for p in evictable if not _is_critical(p)],
            [p for p in evictable if _is_critical(p)],
        ]
        now = self.clock.now()
        for group in groups:
            if group:
                for p in group:
                    not_before, delay = self._evict_backoff.get(
                        p.key(), (0.0, 0.0)
                    )
                    if now < not_before:
                        continue  # still backing off from a prior 429
                    try:
                        self.kube.evict(p)
                        self._evict_backoff.pop(p.key(), None)
                    except TooManyRequestsError as e:
                        delay = (
                            EVICT_BACKOFF_BASE
                            if delay == 0.0
                            else min(delay * 2.0, EVICT_BACKOFF_CAP)
                        )
                        self._evict_backoff[p.key()] = (now + delay, delay)
                        if self.recorder is not None:
                            from karpenter_core_tpu.events import Event

                            self.recorder.publish(Event(
                                involved_object=f"Pod/{p.key()}",
                                type="Warning",
                                reason="FailedDraining",
                                message=str(e),
                            ))
                        continue
                break  # later groups wait for this one to drain
        if any(
            not p.is_daemonset
            for p in self.cluster.pods_on_node(node.name)
        ):
            return  # wait for drain to finish

        # wait for drain-able pods' VolumeAttachments to detach before
        # terminating (controller.go:140-143,190-201); attachments held by
        # non-drain-able pods must not block forever (filterVolumeAttachments)
        if not self._volumes_detached(node):
            return

        # ensure the instance is gone (claims' finalizers handle provider
        # delete; cover unmanaged/orphan nodes too)
        for c in claims:
            try:
                self.cloud_provider.delete(c)
            except NodeClaimNotFoundError:
                pass

        if apilabels.TERMINATION_FINALIZER in node.metadata.finalizers:
            node.metadata.finalizers.remove(apilabels.TERMINATION_FINALIZER)
            try:
                self.kube.update(node)
            except NotFoundError:
                pass  # provider delete already removed the node object

    def _termination_deadline(self, node: Node, claims) -> "float | None":
        """deletionTimestamp + the owning claim's terminationGracePeriod,
        persisted as a node annotation on first computation so the deadline
        survives the claim object (the reference stamps the equivalent
        annotation on the NodeClaim, lifecycle/controller.go:254-269)."""
        stamped = node.metadata.annotations.get(
            apilabels.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY
        )
        if stamped is not None:
            return float(stamped)
        start = node.metadata.deletion_timestamp
        for c in claims:
            tgp = c.spec.termination_grace_period
            if tgp is None:
                continue
            base = (
                c.metadata.deletion_timestamp
                if c.metadata.deletion_timestamp is not None
                else start
            )
            if base is None:
                continue
            deadline = base + tgp
            node.metadata.annotations[
                apilabels.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY
            ] = str(deadline)
            self.kube.update(node)
            return deadline
        return None

    def _volumes_detached(self, node: Node) -> bool:
        """True when no blocking VolumeAttachment remains on the node. An
        attachment blocks only if no non-drain-able pod on the node still
        uses its PV (controller.go:203-237 filterVolumeAttachments)."""
        from karpenter_core_tpu.api.objects import PersistentVolumeClaim
        from karpenter_core_tpu.scheduling.volumeusage import pvc_name_for

        attachments = [
            va
            for va in self.kube.list_volume_attachments()
            if va.node_name == node.name
        ]
        if not attachments:
            return True
        shielded_pvs = set()
        for p in self.cluster.pods_on_node(node.name):
            if podutil.is_evictable(p) and not p.is_daemonset:
                continue  # drain-able: its attachments DO block
            for vol in p.volumes:
                claim_name = pvc_name_for(p, vol)
                if claim_name is None:
                    continue
                pvc = self.kube.get(
                    PersistentVolumeClaim, claim_name, p.metadata.namespace
                )
                if pvc is not None and pvc.volume_name:
                    shielded_pvs.add(pvc.volume_name)
        return all(va.pv_name in shielded_pvs for va in attachments)
