"""Node graceful teardown: taint → drain → instance terminated → finalizer
removed (reference: pkg/controllers/node/termination/controller.go:67-176,
terminator/terminator.go:55-165).
"""
from __future__ import annotations

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import Node
from karpenter_core_tpu.cloudprovider.types import NodeClaimNotFoundError
from karpenter_core_tpu.kube.store import NotFoundError, TooManyRequestsError
from karpenter_core_tpu.scheduling.taints import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_core_tpu.utils import pod as podutil


class NodeTermination:
    def __init__(self, kube, cluster, cloud_provider, clock):
        self.kube = kube
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock

    def reconcile(self, node: Node) -> None:
        if node.metadata.deletion_timestamp is None:
            return
        if apilabels.TERMINATION_FINALIZER not in node.metadata.finalizers:
            return

        # delete owning NodeClaims first (controller.go:178-188)
        claims = [
            c
            for c in self.kube.list_nodeclaims()
            if c.status.provider_id == node.provider_id
        ]
        for c in claims:
            if c.metadata.deletion_timestamp is None:
                self.kube.delete(c)

        # taint so nothing schedules during the drain (terminator.go:55)
        if not any(
            t.key == DISRUPTED_NO_SCHEDULE_TAINT.key for t in node.taints
        ):
            node.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
            self.kube.update(node)

        # drain: non-daemon, evictable pods first; priority grouping is moot
        # with a synchronous eviction stand-in (terminator.go:96-138). A
        # PDB-blocked eviction (429) leaves the pod for the next reconcile —
        # the drain proceeds at the budget's allowed rate (eviction.go:176)
        remaining = [
            p
            for p in self.cluster.pods_on_node(node.name)
            if podutil.is_evictable(p) and not p.is_daemonset
        ]
        for p in remaining:
            try:
                self.kube.evict(p)
            except TooManyRequestsError:
                continue
        if any(
            not p.is_daemonset
            for p in self.cluster.pods_on_node(node.name)
        ):
            return  # wait for drain to finish

        # wait for drain-able pods' VolumeAttachments to detach before
        # terminating (controller.go:140-143,190-201); attachments held by
        # non-drain-able pods must not block forever (filterVolumeAttachments)
        if not self._volumes_detached(node):
            return

        # ensure the instance is gone (claims' finalizers handle provider
        # delete; cover unmanaged/orphan nodes too)
        for c in claims:
            try:
                self.cloud_provider.delete(c)
            except NodeClaimNotFoundError:
                pass

        if apilabels.TERMINATION_FINALIZER in node.metadata.finalizers:
            node.metadata.finalizers.remove(apilabels.TERMINATION_FINALIZER)
            try:
                self.kube.update(node)
            except NotFoundError:
                pass  # provider delete already removed the node object

    def _volumes_detached(self, node: Node) -> bool:
        """True when no blocking VolumeAttachment remains on the node. An
        attachment blocks only if no non-drain-able pod on the node still
        uses its PV (controller.go:203-237 filterVolumeAttachments)."""
        from karpenter_core_tpu.api.objects import PersistentVolumeClaim
        from karpenter_core_tpu.scheduling.volumeusage import pvc_name_for

        attachments = [
            va
            for va in self.kube.list_volume_attachments()
            if va.node_name == node.name
        ]
        if not attachments:
            return True
        shielded_pvs = set()
        for p in self.cluster.pods_on_node(node.name):
            if podutil.is_evictable(p) and not p.is_daemonset:
                continue  # drain-able: its attachments DO block
            for vol in p.volumes:
                claim_name = pvc_name_for(p, vol)
                if claim_name is None:
                    continue
                pvc = self.kube.get(
                    PersistentVolumeClaim, claim_name, p.metadata.namespace
                )
                if pvc is not None and pvc.volume_name:
                    shielded_pvs.add(pvc.volume_name)
        return all(va.pv_name in shielded_pvs for va in attachments)
