"""Pod-trigger batching window (reference: pkg/controllers/provisioning/
batcher.go:33-110; 10s max / 1s idle from options.go:99-100).

Triggers (provisionable-pod events) open a window; the batch closes — and
the provisioner solves — when either no new trigger arrived for
``idle_duration`` or the window has been open ``max_duration``. The batch
boundary IS the solver-invocation boundary: wider batches amortize one
device solve over more pods.
"""
from __future__ import annotations

from typing import Optional


class Batcher:
    def __init__(
        self,
        clock,
        max_duration: float = 10.0,
        idle_duration: float = 1.0,
    ):
        self.clock = clock
        self.max_duration = max_duration
        self.idle_duration = idle_duration
        self._window_start: Optional[float] = None
        self._last_trigger: Optional[float] = None

    def trigger(self) -> None:
        now = self.clock.now()
        if self._window_start is None:
            self._window_start = now
        self._last_trigger = now

    @property
    def open(self) -> bool:
        return self._window_start is not None

    def ready(self) -> bool:
        """The window has closed (batcher.go Wait's two exits)."""
        if self._window_start is None:
            return False
        now = self.clock.now()
        if now - self._window_start >= self.max_duration:
            return True
        return now - self._last_trigger >= self.idle_duration

    def wait_remaining(self) -> float:
        """Seconds until the window would close with no further triggers."""
        if self._window_start is None:
            return 0.0
        now = self.clock.now()
        return max(
            min(
                self.idle_duration - (now - self._last_trigger),
                self.max_duration - (now - self._window_start),
            ),
            0.0,
        )

    def reset(self) -> None:
        self._window_start = None
        self._last_trigger = None
