"""The provisioning reconciler: pending pods → scheduler solve → NodeClaims
(reference: pkg/controllers/provisioning/provisioner.go:74-516).

`schedule()` assembles exactly the inputs the reference does — ready
NodePools in weight order, per-pool instance types, the topology domain
universe, live-cluster SimNodes, daemonset overhead — and runs the selected
solver (`greedy` host FFD or the `tpu` device solver). `provision()` then
materializes NodeClaims (limits-checked, instance types truncated to the 60
cheapest) and returns the pod→target nomination map the binder consumes.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodepool import NodePool
from karpenter_core_tpu.api.objects import Pod
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
    Results,
    Scheduler,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
    Topology,
    domain_universe,
)
from karpenter_core_tpu.utils import pod as podutil
from karpenter_core_tpu.utils import resources as resutil

# how long an existing node stays disruption-protected after pods were
# nominated onto it (statenode nomination TTL; the reference's
# NominationWindow is batch-window-scaled — long enough for the binder's
# conflict-retry loop, short enough not to park consolidation)
NOMINATION_WINDOW = 30.0


class Provisioner:
    def __init__(
        self,
        kube,
        cluster,
        cloud_provider,
        clock,
        solver: str = "greedy",
        device_scheduler_opts: Optional[dict] = None,
        recorder=None,
        solver_client=None,
        unavailable_offerings=None,
        verify_results: bool = True,
        nominated_pods=None,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock
        self.solver = solver
        self.device_scheduler_opts = device_scheduler_opts or {}
        self.recorder = recorder
        # ICE cache (cloudprovider/unavailableofferings.py) shared with the
        # lifecycle controller: every scheduler this provisioner builds —
        # greedy, device, remote, and the disruption simulations routed
        # through new_scheduler — excludes the cached offerings
        self.unavailable_offerings = unavailable_offerings
        # non-None routes tpu solves (and the consolidation sweep) through
        # the solverd sidecar via solver/remote.py; the client owns the
        # circuit breaker, so it outlives individual schedulers
        self.solver_client = solver_client
        # host-side verification of every device/sidecar result
        # (solver/verify.py) before the reconcilers act on it; a rejected
        # result degrades that solve to greedy and emits a Warning event
        self.verify_results = verify_results
        # host+device profiling hook (reference pprof, operator.go:159-175):
        # set by the operator from --profile-solves / --profile-dir
        self.profile_solves = 0
        self.profile_dir = ""
        self._profiled = 0
        # live-nomination view (the operator's binder ledger):
        # {pod key -> target claim/node} for pods already promised
        # capacity whose bind has not landed yet. Two obligations follow
        # (both found by the digital twin's fuzzer under bind-conflict +
        # launch-fault chaos, as capacity overcommits): (1) nominated
        # pods must NOT re-enter the solve — re-placing one double-books
        # the capacity its pending bind is about to take; (2) the solve's
        # existing-node availability must SUBTRACT nominated-but-unbound
        # pods, or other pods get packed into capacity a pending bind
        # already owns. The reference prevents both with cluster-state
        # pod nominations (scheduler.go Reserve + nomination TTLs).
        self._nominated_pods = nominated_pods or (lambda: {})

    # -- input assembly ----------------------------------------------------

    def pending_pods(self) -> List[Pod]:
        nominated = self._nominated_pods()
        return [
            p
            for p in self.kube.list_pods()
            if podutil.is_provisionable(p) and p.key() not in nominated
        ]

    def deleting_node_pods(self) -> List[Pod]:
        """Reschedulable pods on deleting nodes re-enter the solve
        (provisioner.go:159-177)."""
        out = []
        for sn in self.cluster.nodes():
            if not (sn.deleting() or sn.marked_for_deletion):
                continue
            for p in self.cluster.pods_on_node(sn.name):
                if podutil.is_reschedulable(p):
                    out.append(p)
        return out

    def ready_nodepools(self) -> List[NodePool]:
        """Non-deleting pools whose validation/nodeclass conditions aren't
        False, weight-ordered (provisioner.go:215-234)."""
        from karpenter_core_tpu.api.nodepool import (
            COND_NODEPOOL_NODECLASS_READY,
            COND_NODEPOOL_VALIDATION_SUCCEEDED,
        )

        pools = [
            np
            for np in self.kube.list_nodepools()
            if np.metadata.deletion_timestamp is None
            and not np.conditions.is_false(COND_NODEPOOL_VALIDATION_SUCCEEDED)
            and not np.conditions.is_false(COND_NODEPOOL_NODECLASS_READY)
        ]
        pools.sort(key=lambda n: (-n.spec.weight, n.name))
        return pools

    def daemonset_pods(self) -> List[Pod]:
        out = []
        for ds in self.kube.list_daemonsets():
            if ds.pod_template is not None:
                p = ds.pod_template
                p.is_daemonset = True
                out.append(p)
        return out

    def _profiled_solve(self, scheduler, pods):
        """cProfile the host path + capture a jax.profiler trace of the
        device path for one solve (the pprof/xprof stand-in)."""
        import cProfile
        import os

        os.makedirs(self.profile_dir or ".", exist_ok=True)
        n = self._profiled
        self._profiled += 1
        prof = cProfile.Profile()
        trace_dir = os.path.join(self.profile_dir, f"solve-{n}-xla")
        try:
            import jax

            jax.profiler.start_trace(trace_dir)
            traced = True
        except Exception:
            traced = False
        prof.enable()
        try:
            return scheduler.solve(pods)
        finally:
            prof.disable()
            if traced:
                import jax

                jax.profiler.stop_trace()
            prof.dump_stats(
                os.path.join(self.profile_dir, f"solve-{n}.pprof")
            )

    # -- the solve ---------------------------------------------------------

    def new_scheduler(self, pods: List[Pod], excluded_nodes=frozenset()):
        """Scheduler over the live cluster minus ``excluded_nodes`` — the
        shared assembly for the real solve and the disruption simulation
        (helpers.go:49-113 builds its sim the same way)."""
        nodepools = self.ready_nodepools()
        instance_types = {
            np.name: self.cloud_provider.get_instance_types(np)
            for np in nodepools
        }
        sim_nodes = [
            n
            for n in self.cluster.sim_nodes()
            if n.name not in excluded_nodes
        ]
        self._attach_volume_state(sim_nodes)
        self._reserve_nominated(sim_nodes)
        topology = Topology(
            domains=domain_universe(nodepools, instance_types, sim_nodes),
            existing_pods=[
                t
                for t in self.cluster.existing_pod_triples()
                if t[2] not in excluded_nodes
            ],
            excluded_pod_uids={p.uid for p in pods},
        )
        unavail = (
            self.unavailable_offerings.snapshot()
            if self.unavailable_offerings is not None
            else frozenset()
        )
        common = dict(
            nodepools=nodepools,
            instance_types=instance_types,
            existing_nodes=sim_nodes,
            daemonset_pods=self.daemonset_pods(),
            unavailable_offerings=unavail,
        )
        if self.solver == "tpu":
            if self.solver_client is not None:
                from karpenter_core_tpu.solver.remote import RemoteScheduler

                return RemoteScheduler(
                    self.solver_client,
                    topology=topology,
                    device_scheduler_opts=self.device_scheduler_opts,
                    verify=self.verify_results,
                    recorder=self.recorder,
                    **common,
                )
            from karpenter_core_tpu.models.provisioner import DeviceScheduler

            return DeviceScheduler(
                topology=topology, verify=self.verify_results,
                recorder=self.recorder,
                **common, **self.device_scheduler_opts,
            )
        return Scheduler(topology=topology, **common)

    def schedule(self) -> Tuple[Results, List[Pod]]:
        from karpenter_core_tpu.metrics import wiring as m

        pods = self.pending_pods() + self.deleting_node_pods()
        if not pods:
            return Results([], [], {}), []
        pods, volume_errors = self._prepare_volumes(pods)
        m.QUEUE_DEPTH.set(len(pods))
        m.IGNORED_PODS.set(len(volume_errors))
        if not pods:
            return Results([], [], volume_errors), []
        scheduler = self.new_scheduler(pods)
        with m.SCHEDULING_DURATION.time():
            if self._profiled < self.profile_solves:
                results = self._profiled_solve(scheduler, pods)
            else:
                results = scheduler.solve(pods)
        results.pod_errors.update(volume_errors)
        m.UNSCHEDULABLE_PODS.set(len(results.pod_errors))
        if self.recorder is not None and results.pod_errors:
            from karpenter_core_tpu.events import Event

            by_uid = {p.uid: p for p in pods}
            self.recorder.publish(*[
                Event(
                    involved_object=f"Pod/{by_uid[uid].key()}",
                    type="Warning",
                    reason="FailedScheduling",
                    message=msg,
                )
                for uid, msg in results.pod_errors.items()
                if uid in by_uid
            ])
        return results, pods

    # -- volume preprocessing (volumetopology.go inject+validate,
    # provisioner.go:436-516) ---------------------------------------------

    def _prepare_volumes(self, pods: List[Pod]):
        from karpenter_core_tpu.controllers.provisioning.scheduling.volumetopology import (
            VolumeTopology,
        )
        from karpenter_core_tpu.scheduling.volumeusage import get_volumes

        vt = VolumeTopology(self.kube)
        keep: List[Pod] = []
        errors: Dict[str, str] = {}
        for p in pods:
            if not p.volumes:
                keep.append(p)
                continue
            err = vt.validate_pvcs(p)
            if err is not None:
                errors[p.uid] = err
                continue
            vt.inject(p)
            p.resolved_volumes = get_volumes(self.kube, p) or None
            keep.append(p)
        return keep, errors

    def _reserve_nominated(self, sim_nodes) -> None:
        """Subtract nominated-but-unbound pods from their target node's
        availability: capacity a pending bind owns is not free. Pods
        nominated to an UNREGISTERED claim have no sim node yet and need
        no reservation — the claim's capacity only becomes a solve
        target after registration, and the binder lands (or prunes) the
        nominations earlier in that same pass."""
        nominated = self._nominated_pods()
        if not nominated:
            return
        pending_by_node: Dict[str, List[Pod]] = {}
        for key in sorted(nominated):
            ns, _, name = key.partition("/")
            pod = self.kube.get(Pod, name, ns)
            if pod is None or pod.node_name:
                continue  # gone, or the bind already landed
            pending_by_node.setdefault(nominated[key], []).append(pod)
        for sim in sim_nodes:
            pending = pending_by_node.get(sim.name)
            if not pending:
                continue
            # requests_for_pods already folds in the implicit 'pods'
            # count resource, so ONE subtract covers cpu/memory/slots
            sim.available = resutil.subtract(
                sim.available, resutil.requests_for_pods(*pending)
            )

    def _attach_volume_state(self, sim_nodes) -> None:
        """Per-node CSINode limits + bound pods' volume usage
        (statenode volume tracking, volumeusage.go Add/AddLimit)."""
        from karpenter_core_tpu.api.objects import CSINode
        from karpenter_core_tpu.scheduling.volumeusage import (
            VolumeUsage,
            get_volumes,
        )

        for sn in sim_nodes:
            csinode = self.kube.get(CSINode, sn.name)
            if csinode is None:
                continue
            usage = VolumeUsage()
            for driver, allocatable in csinode.drivers:
                usage.add_limit(driver, allocatable)
            for p in self.cluster.pods_on_node(sn.name):
                if p.resolved_volumes is None and p.volumes:
                    # stamp once; volumes are immutable between binds
                    p.resolved_volumes = get_volumes(self.kube, p) or {}
                if p.resolved_volumes:
                    usage.add(p.resolved_volumes)
            sn.volume_usage = usage

    # -- output: NodeClaims + nominations ----------------------------------

    def provision(self) -> Dict[str, str]:
        """One reconcile: solve and create NodeClaims. Returns nominations:
        pod key → existing node name or new NodeClaim name."""
        results, _ = self.schedule()
        nominations: Dict[str, str] = {}

        # eviction claims FIRST (drain-before-bind, gangsched ISSUE 10):
        # preempted placements assume the victims' freed capacity, so the
        # victims are evicted before their nodes are nominated — the
        # binder's capacity view converges as the drains complete
        self._execute_evictions(results)

        for sim in results.existing_nodes:
            for p in sim.pods:
                nominations[p.key()] = sim.name
            if sim.pods:
                # protect the node from disruption while the binds land
                # (StateNode.nominated gates candidacy, disruption/types
                # .py; the reference's NominateNodeEvent + TTL — this was
                # the dormant half of that contract)
                self.cluster.nominate_node(
                    sim.name, self.clock.now() + NOMINATION_WINDOW
                )
        if self.recorder is not None and nominations:
            from karpenter_core_tpu.events import Event

            self.recorder.publish(*[
                Event(
                    involved_object=f"Pod/{key}",
                    type="Normal",
                    reason="Nominated",
                    message=f"Pod should schedule on {target}",
                )
                for key, target in nominations.items()
            ])

        usage_by_pool = self._usage_by_nodepool()
        pools = {np.name: np for np in self.kube.list_nodepools()}
        for claim in results.new_node_claims:
            pool = pools.get(claim.template.nodepool_name)
            if pool is not None and pool.spec.limits:
                # pessimistic max-capacity check (provisioner.go:354-392)
                max_cap = resutil.cmp_max(
                    *(it.capacity for it in claim.instance_type_options)
                )
                usage = usage_by_pool.get(pool.name, {})
                projected = resutil.merge(usage, max_cap)
                errs = pool.spec.limits.exceeded_by(projected)
                if errs:
                    # pods stay pending, but VISIBLY (the greedy solve
                    # reports limit failures in-solve; the device solve
                    # reports them here at claim-creation time). The counter
                    # makes near-limit solve→drop→re-solve churn observable.
                    from karpenter_core_tpu.metrics import wiring as m

                    m.SOLVER_LIMIT_DROPPED_CLAIMS.inc(
                        {"nodepool": pool.name}
                    )
                    if self.recorder is not None:
                        from karpenter_core_tpu.events import Event

                        self.recorder.publish(*[
                            Event(
                                involved_object=f"Pod/{p.key()}",
                                type="Warning",
                                reason="FailedScheduling",
                                message=(
                                    f"nodepool {pool.name!r} limit "
                                    f"exceeded: {'; '.join(errs)}"
                                ),
                            )
                            for p in claim.pods
                        ])
                    continue  # skip launch
                usage_by_pool[pool.name] = projected
            nc = claim.template.to_node_claim(
                claim.requirements, claim.instance_type_options, claim.requests
            )
            nc.metadata.finalizers.append(apilabels.TERMINATION_FINALIZER)
            self.kube.create(nc)
            for p in claim.pods:
                nominations[p.key()] = nc.name
        return nominations

    def _execute_evictions(self, results: Results) -> None:
        """Turn verified eviction claims into API evictions. Claims were
        verified legal by solver/verify.py (every victim strictly lower
        tier than a pod its capacity admitted) before the result reached
        this reconciler; a victim that vanished since the snapshot is a
        no-op (its capacity is already free)."""
        evictions = getattr(results, "evictions", None)
        if not evictions:
            return
        from karpenter_core_tpu.metrics import wiring as m

        for node_name, uids in sorted(evictions.items()):
            # claims name the victim's node: resolve uids against THAT
            # node's bound pods only, not a cluster-wide scan
            by_uid = {
                p.uid: p for p in self.cluster.pods_on_node(node_name)
            }
            for uid in uids:
                victim = by_uid.get(uid)
                if victim is None:
                    continue
                self.kube.evict(victim)
                m.SOLVER_PREEMPTION_EVICTIONS.inc()
                if self.recorder is not None:
                    from karpenter_core_tpu.events import Event

                    self.recorder.publish(Event(
                        involved_object=f"Pod/{victim.key()}",
                        type="Normal",
                        reason="Preempted",
                        message=(
                            f"evicted from {node_name} to admit a"
                            " higher-priority pod (drain-before-bind)"
                        ),
                    ))

    def _usage_by_nodepool(self) -> Dict[str, dict]:
        """In-use capacity per pool (the nodepool.counter aggregation,
        reference pkg/controllers/nodepool/counter)."""
        usage: Dict[str, dict] = {}
        for sn in self.cluster.nodes():
            pool = sn.nodepool_name
            if pool:
                usage[pool] = resutil.merge(usage.get(pool, {}), sn.capacity())
        return usage
