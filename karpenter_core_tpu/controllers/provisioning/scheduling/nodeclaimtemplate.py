"""NodeClaimTemplate + instance-type filtering
(reference: scheduling/nodeclaimtemplate.go:33-96, nodeclaim.go:248-300)."""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodeclaim import NodeClaim, NodeClaimSpec
from karpenter_core_tpu.api.nodepool import NodePool
from karpenter_core_tpu.api.objects import NodeSelectorRequirement, ObjectMeta
from karpenter_core_tpu.cloudprovider.types import (
    InstanceType,
    order_by_price,
    satisfies_min_values,
)
from karpenter_core_tpu.scheduling import Requirement, Requirements
from karpenter_core_tpu.scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
)
from karpenter_core_tpu.utils import resources as resutil

# Launch-side truncation of the viable instance-type list
# (nodeclaimtemplate.go:33-35).
MAX_INSTANCE_TYPES = 60

_claim_counter = itertools.count(1)


@dataclass
class NodeClaimTemplate:
    nodepool_name: str
    nodepool_uid: str
    requirements: Requirements
    instance_type_options: List[InstanceType]
    taints: list
    startup_taints: list
    labels: dict
    annotations: dict
    spec: NodeClaimSpec

    @classmethod
    def from_nodepool(cls, nodepool: NodePool) -> "NodeClaimTemplate":
        tmpl = nodepool.spec.template
        labels = dict(tmpl.labels)
        labels[apilabels.NODEPOOL_LABEL_KEY] = nodepool.name
        annotations = dict(tmpl.annotations)
        annotations[apilabels.NODEPOOL_HASH_ANNOTATION_KEY] = nodepool.static_hash()
        # version travels with the hash so drift's annotation-vs-annotation
        # compare is gated on matching hash algorithms
        # (nodeclaimtemplate.go stamps both; hash/controller.go migrates)
        annotations[apilabels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = (
            apilabels.HASH_VERSION
        )
        requirements = Requirements()
        requirements.add(
            *Requirements.from_node_selector_requirements_with_min_values(
                tmpl.requirements
            ).values()
        )
        requirements.add(*Requirements.from_labels(labels).values())
        return cls(
            nodepool_name=nodepool.name,
            nodepool_uid=nodepool.metadata.uid,
            requirements=requirements,
            instance_type_options=[],
            taints=list(tmpl.taints),
            startup_taints=list(tmpl.startup_taints),
            labels=labels,
            annotations=annotations,
            spec=NodeClaimSpec(
                node_class_ref=tmpl.node_class_ref,
                taints=list(tmpl.taints),
                startup_taints=list(tmpl.startup_taints),
                expire_after=tmpl.expire_after,
                termination_grace_period=tmpl.termination_grace_period,
            ),
        )

    def to_node_claim(self, requirements: Requirements,
                      instance_types: List[InstanceType],
                      requests: dict) -> NodeClaim:
        """Materialize a launchable NodeClaim, truncating the instance-type
        list to the MAX_INSTANCE_TYPES cheapest (nodeclaimtemplate.go:69-96)."""
        its = order_by_price(instance_types, requirements)[:MAX_INSTANCE_TYPES]
        final = requirements.copy()
        final.add(
            Requirement.new(
                apilabels.LABEL_INSTANCE_TYPE,
                "In",
                [it.name for it in its],
                min_values=requirements.get(apilabels.LABEL_INSTANCE_TYPE).min_values,
            )
        )
        nc = NodeClaim(
            metadata=ObjectMeta(
                name=f"{self.nodepool_name}-{next(_claim_counter):05d}",
                labels=dict(self.labels),
                annotations=dict(self.annotations),
            ),
            spec=NodeClaimSpec(
                requirements=[
                    _to_nsr(r) for r in final.values()
                ],
                resources_requests=dict(requests),
                node_class_ref=self.spec.node_class_ref,
                taints=list(self.taints),
                startup_taints=list(self.startup_taints),
                expire_after=self.spec.expire_after,
                termination_grace_period=self.spec.termination_grace_period,
            ),
        )
        nc.metadata.labels[apilabels.NODEPOOL_LABEL_KEY] = self.nodepool_name
        return nc


def _to_nsr(req) -> NodeSelectorRequirement:
    op = req.operator()
    values: tuple = ()
    if op in ("In", "NotIn"):
        values = tuple(req.sorted_values())
    elif req.greater_than is not None:
        op, values = "Gt", (str(req.greater_than),)
    elif req.less_than is not None:
        op, values = "Lt", (str(req.less_than),)
    return NodeSelectorRequirement(
        key=req.key, operator=op, values=values, min_values=req.min_values
    )


@dataclass
class FilterResults:
    """Pairwise failure-reason bookkeeping (nodeclaim.go:150-246)."""

    remaining: List[InstanceType] = field(default_factory=list)
    requirements_met: bool = False
    fits: bool = False
    has_offering: bool = False
    requirements_and_fits: bool = False
    requirements_and_offering: bool = False
    fits_and_offering: bool = False
    min_values_error: Optional[str] = None

    def failure_reason(self) -> str:
        if self.min_values_error:
            return self.min_values_error
        if not self.requirements_met:
            return "did not meet scheduling requirements"
        if not self.fits:
            return "no instance type has enough resources"
        if not self.has_offering:
            return "no instance type has a compatible available offering"
        if not self.requirements_and_fits:
            return "no instance type which met the scheduling requirements and had enough resources"
        if not self.requirements_and_offering:
            return "no instance type which met the scheduling requirements and had a compatible offering"
        if not self.fits_and_offering:
            return "no instance type which had enough resources and had a compatible offering"
        return "no instance type met the requirements/resources/offering tuple"


def filter_instance_types(
    instance_types: List[InstanceType],
    requirements: Requirements,
    requests: dict,
) -> FilterResults:
    """Keep instance types meeting requirements+fit+offering simultaneously,
    tracking which pairs of criteria were ever met for error reporting
    (nodeclaim.go:248-300)."""
    results = FilterResults()
    for it in instance_types:
        compat = not it.requirements.intersects(requirements)
        it_fits = resutil.fits(requests, it.allocatable())
        has_offering = it.offerings.available().has_compatible(requirements)

        results.requirements_met = results.requirements_met or compat
        results.fits = results.fits or it_fits
        results.has_offering = results.has_offering or has_offering
        results.requirements_and_fits = results.requirements_and_fits or (
            compat and it_fits and not has_offering
        )
        results.requirements_and_offering = results.requirements_and_offering or (
            compat and has_offering and not it_fits
        )
        results.fits_and_offering = results.fits_and_offering or (
            it_fits and has_offering and not compat
        )
        if compat and it_fits and has_offering:
            results.remaining.append(it)

    if requirements.has_min_values():
        _, err = satisfies_min_values(results.remaining, requirements)
        if err is not None:
            results.min_values_error = err
            results.remaining = []
    return results
