"""Pod scheduling queue, CPU-then-memory descending with progress detection
(reference: pkg/controllers/provisioning/scheduling/queue.go:31-112)."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.api.objects import Pod


def by_cpu_and_memory_descending(pods: List[Pod], pod_requests: Dict[str, dict]) -> List[Pod]:
    def sort_key(p: Pod):
        r = pod_requests[p.uid]
        return (
            -r.get("cpu", 0.0),
            -r.get("memory", 0.0),
            p.metadata.creation_timestamp,
            p.uid,
        )

    return sorted(pods, key=sort_key)


class Queue:
    def __init__(self, pods: List[Pod], pod_requests: Dict[str, dict]):
        self.pods: List[Pod] = by_cpu_and_memory_descending(list(pods), pod_requests)
        self.last_len: Dict[str, int] = {}

    def pop(self) -> Tuple[Optional[Pod], bool]:
        if not self.pods:
            return None, False
        p = self.pods[0]
        # no progress since this pod was last pushed at this queue length
        if self.last_len.get(p.uid) == len(self.pods):
            return None, False
        self.pods = self.pods[1:]
        return p, True

    def push(self, pod: Pod, relaxed: bool) -> None:
        self.pods.append(pod)
        if relaxed:
            self.last_len = {}
        else:
            self.last_len[pod.uid] = len(self.pods)

    def list(self) -> List[Pod]:
        return self.pods
