"""Topology tracking interface.

The reference Topology (pkg/controllers/provisioning/scheduling/topology.go:41-321)
tracks topology-spread / pod-affinity / pod-anti-affinity domain counts and
tightens requirements per pod placement. Round 1 ships the interface with
hostname-domain registration (enough for requirement bookkeeping and the
resource/requirements/taints bench configs); spread/affinity group counting
is the dedicated topology milestone — the device-side formulation keeps
per-group domain-count vectors and computes skew as max-min over the count
tensor.
"""
from __future__ import annotations

from typing import List, Optional

from karpenter_core_tpu.api.objects import Pod
from karpenter_core_tpu.scheduling import Requirements


class Topology:
    def __init__(self):
        self.domains: dict = {}  # key -> set of registered domain values

    def register(self, key: str, value: str) -> None:
        self.domains.setdefault(key, set()).add(value)

    def unregister(self, key: str, value: str) -> None:
        self.domains.get(key, set()).discard(value)

    def add_requirements(
        self,
        strict_pod_requirements: Requirements,
        node_requirements: Requirements,
        pod: Pod,
        allow_undefined=frozenset(),
    ) -> Requirements:
        """Topology-derived extra requirements for placing pod on this node.
        No spread/affinity groups yet -> no tightening."""
        return Requirements()

    def record(self, pod: Pod, requirements: Requirements, allow_undefined=frozenset()) -> None:
        pass

    def update(self, pod: Pod) -> None:
        """Recompute groups after a relaxation changed the pod's constraints."""
        pass
