"""Topology tracking: spread / pod-affinity / pod-anti-affinity domain counts.

Host-side twin of the reference's Topology machinery
(reference: pkg/controllers/provisioning/scheduling/topology.go:41-321,
topologygroup.go:56-342, topologynodefilter.go:30-80). Each constraint class
becomes a TopologyGroup — "SELECT COUNT(*) FROM pods GROUP BY(topology_key)"
restricted to a namespace set + label selector — and placement tightens a
pod's requirements to the next admissible domain:

* spread: domains where count (+1 if self-selecting) - min <= maxSkew;
* affinity: domains that already hold a selected pod (or any domain, to
  bootstrap a self-selecting group);
* anti-affinity: domains that hold none (tracked via emptyDomains);
* inverse anti-affinity: OTHER pods' anti-affinity terms, so a new pod whose
  labels match an existing term's selector avoids that pod's domains.

Device-side note: these groups lower to the kernel's count tensors
(ops/topoplan.py — zone count vectors, per-slot hostname counts, skew
rules in ops/ffd.py); the host algebra here is the parity oracle and the
fallback for shapes the planner rules device-ineligible.

Deliberate ordering deviation from the reference: ``register`` also inserts
the domain into the universe (`self.domains`), so groups created after an
in-flight claim or existing node registered its hostname still see it; the
reference achieves the same only through construction ordering.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import (
    POD_FAILED,
    POD_SUCCEEDED,
    LabelSelector,
    Pod,
)
from karpenter_core_tpu.scheduling import Requirements
from karpenter_core_tpu.scheduling.requirement import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    Requirement,
)

MAX_SKEW_UNBOUNDED = 1 << 31  # affinity groups never constrain skew

TYPE_SPREAD = "topology spread"
TYPE_AFFINITY = "pod affinity"
TYPE_ANTI_AFFINITY = "pod anti-affinity"


class TopologyError(Exception):
    """A topology constraint admits no domain on this node
    (topology.go topologyError:88-99)."""


def ignored_for_topology(pod: Pod) -> bool:
    """Unscheduled / terminal / terminating pods don't count
    (topology.go IgnoredForTopology:418-420)."""
    return (
        not pod.node_name
        or pod.phase in (POD_SUCCEEDED, POD_FAILED)
        or pod.metadata.deletion_timestamp is not None
    )


def has_pod_anti_affinity(pod: Pod) -> bool:
    return bool(
        pod.affinity
        and pod.affinity.pod_anti_affinity
        and (
            pod.affinity.pod_anti_affinity.required
            or pod.affinity.pod_anti_affinity.preferred
        )
    )


def has_required_pod_anti_affinity(pod: Pod) -> bool:
    return bool(
        pod.affinity
        and pod.affinity.pod_anti_affinity
        and pod.affinity.pod_anti_affinity.required
    )


def has_topology_constraints(pod: Pod) -> bool:
    """Pods with any topology-coupled constraint take the host scheduling
    path; the device FFD batches the dominant constraint shapes and falls
    back here for the exotic rest (ops/topoplan.py eligibility)."""
    return bool(
        pod.topology_spread_constraints
        or (
            pod.affinity
            and (pod.affinity.pod_affinity or pod.affinity.pod_anti_affinity)
        )
    )


class TopologyNodeFilter:
    """OR-of-Requirements deciding which nodes count for a spread
    (topologynodefilter.go:30-80). Empty filter matches everything."""

    def __init__(self, alternatives: Optional[List[Requirements]] = None):
        self.alternatives = alternatives or []

    @classmethod
    def for_pod(cls, pod: Pod) -> "TopologyNodeFilter":
        selector_reqs = Requirements.from_labels(pod.node_selector)
        affinity = pod.affinity.node_affinity if pod.affinity else None
        if affinity is None or not affinity.required:
            return cls([selector_reqs])
        alternatives = []
        for term in affinity.required:
            reqs = Requirements()
            reqs.add(*selector_reqs.copy().values())
            reqs.add(
                *Requirements.from_node_selector_requirements(
                    term.match_expressions
                ).values()
            )
            alternatives.append(reqs)
        return cls(alternatives)

    def matches_labels(self, labels: dict) -> bool:
        return self.matches_requirements(Requirements.from_labels(labels))

    def matches_requirements(
        self, requirements: Requirements, allow_undefined: frozenset = frozenset()
    ) -> bool:
        if not self.alternatives:
            return True
        return any(
            requirements.is_compatible(alt, allow_undefined)
            for alt in self.alternatives
        )

    def signature(self) -> tuple:
        return tuple(
            tuple(sorted((k, hash(r)) for k, r in alt.items()))
            for alt in self.alternatives
        )


class TopologyGroup:
    """Domain counters for one constraint shape (topologygroup.go:56-99).
    Identical shapes across pods share one group keyed by signature()."""

    def __init__(
        self,
        group_type: str,
        key: str,
        pod: Optional[Pod],
        namespaces: Set[str],
        selector: Optional[LabelSelector],
        max_skew: int,
        min_domains: Optional[int],
        domains: Iterable[str],
    ):
        self.type = group_type
        self.key = key
        self.max_skew = max_skew
        self.min_domains = min_domains
        self.namespaces = frozenset(namespaces)
        self.selector = selector
        # only spread constraints filter which nodes participate
        self.node_filter = (
            TopologyNodeFilter.for_pod(pod)
            if group_type == TYPE_SPREAD and pod is not None
            else TopologyNodeFilter()
        )
        self.owners: Set[str] = set()
        self.domains: Dict[str, int] = {d: 0 for d in domains}
        self.empty_domains: Set[str] = set(self.domains)

    # -- identity ----------------------------------------------------------

    def signature(self) -> tuple:
        """Dedup key: one group tracks many owner pods with the same shape
        (topologygroup.go Hash:159-175; minDomains deliberately excluded,
        matching the reference)."""
        return (
            self.type,
            self.key,
            self.namespaces,
            self.selector,
            self.max_skew,
            self.node_filter.signature(),
        )

    # -- counting ----------------------------------------------------------

    def record(self, *domains: str) -> None:
        for d in domains:
            self.domains[d] = self.domains.get(d, 0) + 1
            self.empty_domains.discard(d)

    def register(self, *domains: str) -> None:
        for d in domains:
            if d not in self.domains:
                self.domains[d] = 0
                self.empty_domains.add(d)

    def unregister(self, *domains: str) -> None:
        for d in domains:
            self.domains.pop(d, None)
            self.empty_domains.discard(d)

    def selects(self, pod: Pod) -> bool:
        """Namespace + label-selector match; a None selector selects nothing
        (LabelSelectorAsSelector(nil) == Nothing)."""
        return (
            pod.metadata.namespace in self.namespaces
            and self.selector is not None
            and self.selector.matches(pod.metadata.labels)
        )

    def counts(
        self,
        pod: Pod,
        requirements: Requirements,
        allow_undefined: frozenset = frozenset(),
    ) -> bool:
        """Would this pod count for the group if it lands on a node with the
        given requirements (topologygroup.go:121-124)."""
        return self.selects(pod) and self.node_filter.matches_requirements(
            requirements, allow_undefined
        )

    # -- owners ------------------------------------------------------------

    def add_owner(self, uid: str) -> None:
        self.owners.add(uid)

    def remove_owner(self, uid: str) -> None:
        self.owners.discard(uid)

    def is_owned_by(self, uid: str) -> bool:
        return uid in self.owners

    # -- next-domain selection --------------------------------------------

    def get(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        if self.type == TYPE_SPREAD:
            return self._next_domain_spread(pod, pod_domains, node_domains)
        if self.type == TYPE_AFFINITY:
            return self._next_domain_affinity(pod, pod_domains, node_domains)
        return self._next_domain_anti_affinity(pod_domains, node_domains)

    def _candidate_domains(self, node_domains: Requirement) -> Iterable[str]:
        """Iterate the smaller side when the node pins explicit values
        (topologygroup.go:195-230)."""
        if node_domains.operator() == OP_IN:
            return [d for d in node_domains.sorted_values() if d in self.domains]
        return [d for d in sorted(self.domains) if node_domains.has(d)]

    def _next_domain_spread(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        """'existing matching num' + 'if self-match' - 'global min' <= maxSkew
        (topologygroup.go:181-227)."""
        min_count = self._domain_min_count(pod_domains)
        self_selecting = self.selects(pod)
        best_domain = None
        best_count = None
        for domain in self._candidate_domains(node_domains):
            count = self.domains[domain]
            if self_selecting:
                count += 1
            if count - min_count <= self.max_skew and (
                best_count is None or count < best_count
            ):
                best_domain = domain
                best_count = count
        if best_domain is None:
            return Requirement.new(pod_domains.key, OP_DOES_NOT_EXIST)
        return Requirement.new(pod_domains.key, OP_IN, [best_domain])

    def _domain_min_count(self, pod_domains: Requirement) -> int:
        """Min count across pod-admissible domains; hostname topologies float
        at zero since a new node is always creatable; minDomains forces zero
        while under-provisioned (topologygroup.go:229-249)."""
        if self.key == apilabels.LABEL_HOSTNAME:
            return 0
        min_count = None
        supported = 0
        for domain, count in self.domains.items():
            if pod_domains.has(domain):
                supported += 1
                if min_count is None or count < min_count:
                    min_count = count
        if self.min_domains is not None and supported < self.min_domains:
            return 0
        return min_count if min_count is not None else (1 << 31)

    def _next_domain_affinity(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        """(topologygroup.go:253-300)"""
        options = Requirement.new(pod_domains.key, OP_DOES_NOT_EXIST)
        for domain in self._candidate_domains(node_domains):
            if pod_domains.has(domain) and self.domains[domain] > 0:
                options.values.add(domain)
        if options.values:
            return options

        # Bootstrap: self-selecting pod and nothing placed yet (or placed
        # only in pod-incompatible domains) may pick a domain, preferring the
        # pod∩node intersection (keeps in-flight nodes' own domains).
        if self.selects(pod) and (
            len(self.domains) == len(self.empty_domains)
            or not self._any_compatible_pod_domain(pod_domains)
        ):
            intersected = pod_domains.intersection(node_domains)
            for domain in sorted(self.domains):
                if intersected.has(domain):
                    options.values.add(domain)
                    break
            for domain in sorted(self.domains):
                if pod_domains.has(domain):
                    options.values.add(domain)
                    break
        return options

    def _any_compatible_pod_domain(self, pod_domains: Requirement) -> bool:
        return any(
            pod_domains.has(domain) and count > 0
            for domain, count in self.domains.items()
        )

    def _next_domain_anti_affinity(
        self, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        """Only empty domains admit the pod (topologygroup.go:316-342)."""
        options = Requirement.new(pod_domains.key, OP_DOES_NOT_EXIST)
        if node_domains.operator() == OP_IN and node_domains.length() < len(
            self.empty_domains
        ):
            for domain in node_domains.sorted_values():
                if domain in self.empty_domains and pod_domains.has(domain):
                    options.values.add(domain)
        else:
            for domain in sorted(self.empty_domains):
                if node_domains.has(domain) and pod_domains.has(domain):
                    options.values.add(domain)
        return options


class Topology:
    """Group registry + the AddRequirements/Record protocol the in-flight
    node entities drive (topology.go:41-58)."""

    def __init__(
        self,
        domains: Optional[Dict[str, Set[str]]] = None,
        existing_pods: Optional[List[Tuple[Pod, dict, str]]] = None,
        excluded_pod_uids: Iterable[str] = (),
    ):
        # universe of domains per topology key (provisioner.go:251-283)
        self.domains: Dict[str, Set[str]] = {
            k: set(v) for k, v in (domains or {}).items()
        }
        # (pod, node_labels, node_name) triples for domain counting; the
        # cluster-state layer supplies these (topology.go countDomains)
        self.existing_pods = list(existing_pods or [])
        self.excluded_pods: Set[str] = set(excluded_pod_uids)
        self.topologies: Dict[tuple, TopologyGroup] = {}
        self.inverse_topologies: Dict[tuple, TopologyGroup] = {}
        self._inverse_initialized = False
        # reverse owner index: pod uid -> the (deduped) groups it owns, in
        # the pod's constraint order. update() and _matching_topologies are
        # both O(all groups × pods) without it — a 3s host tax per 50k-pod
        # solve. The reference scans its group map per pod too, but Go map
        # iteration order is randomized, so constraint order here is just as
        # faithful.
        self._owned: Dict[str, List[TopologyGroup]] = {}

    # -- group construction ------------------------------------------------

    def ensure_inverse_initialized(self) -> None:
        """Build inverse anti-affinity groups from existing cluster pods.
        update() does this lazily; callers that skip update() for
        constraint-free pods must call it once per solve instead."""
        if not self._inverse_initialized:
            self._update_inverse_affinities()
            self._inverse_initialized = True

    def update(self, pod: Pod) -> None:
        """(Re)build the groups this pod owns; called for every pod entering
        a solve and again after each relaxation (topology.go:105-140)."""
        self.ensure_inverse_initialized()

        for group in self._owned.pop(pod.uid, ()):
            group.remove_owner(pod.uid)

        if has_required_pod_anti_affinity(pod):
            self._update_inverse_anti_affinity(pod, None)

        owned: Dict[int, TopologyGroup] = {}
        for group in self._new_for_topologies(pod) + self._new_for_affinities(pod):
            sig = group.signature()
            existing = self.topologies.get(sig)
            if existing is None:
                self._count_domains(group)
                self.topologies[sig] = group
                existing = group
            existing.add_owner(pod.uid)
            owned[id(existing)] = existing
        if owned:
            self._owned[pod.uid] = list(owned.values())

    def _new_for_topologies(self, pod: Pod) -> List[TopologyGroup]:
        return [
            TopologyGroup(
                TYPE_SPREAD,
                cs.topology_key,
                pod,
                {pod.metadata.namespace},
                cs.label_selector,
                cs.max_skew,
                cs.min_domains,
                self.domains.get(cs.topology_key, set()),
            )
            for cs in pod.topology_spread_constraints
        ]

    def _new_for_affinities(self, pod: Pod) -> List[TopologyGroup]:
        """Both hard and soft terms build groups; relaxation later strips the
        soft ones and re-calls update (topology.go:322-358)."""
        groups = []
        if pod.affinity is None:
            return groups
        for group_type, spec in (
            (TYPE_AFFINITY, pod.affinity.pod_affinity),
            (TYPE_ANTI_AFFINITY, pod.affinity.pod_anti_affinity),
        ):
            if spec is None:
                continue
            terms = list(spec.required) + [w.pod_affinity_term for w in spec.preferred]
            for term in terms:
                groups.append(
                    TopologyGroup(
                        group_type,
                        term.topology_key,
                        pod,
                        self._namespace_list(pod, term),
                        term.label_selector,
                        MAX_SKEW_UNBOUNDED,
                        None,
                        self.domains.get(term.topology_key, set()),
                    )
                )
        return groups

    def _namespace_list(self, pod: Pod, term) -> Set[str]:
        if not term.namespaces:
            return {pod.metadata.namespace}
        return set(term.namespaces)

    def _update_inverse_affinities(self) -> None:
        """Track existing pods' anti-affinity terms so newly scheduled pods
        avoid their domains (topology.go:224-240)."""
        for pod, node_labels, node_name in self.existing_pods:
            if pod.uid in self.excluded_pods or ignored_for_topology(pod):
                continue
            if has_required_pod_anti_affinity(pod):
                labels = dict(node_labels)
                labels.setdefault(apilabels.LABEL_HOSTNAME, node_name)
                self._update_inverse_anti_affinity(pod, labels)

    def _update_inverse_anti_affinity(
        self, pod: Pod, node_labels: Optional[dict]
    ) -> None:
        """Inverse groups track only REQUIRED terms — preferences of other
        pods are not enforced (topology.go:244-269)."""
        for term in pod.affinity.pod_anti_affinity.required:
            group = TopologyGroup(
                TYPE_ANTI_AFFINITY,
                term.topology_key,
                pod,
                self._namespace_list(pod, term),
                term.label_selector,
                MAX_SKEW_UNBOUNDED,
                None,
                self.domains.get(term.topology_key, set()),
            )
            sig = group.signature()
            existing = self.inverse_topologies.get(sig)
            if existing is None:
                self.inverse_topologies[sig] = group
                existing = group
            if node_labels is not None and group.key in node_labels:
                existing.record(node_labels[group.key])
            existing.add_owner(pod.uid)

    def _count_domains(self, group: TopologyGroup) -> None:
        """Seed counts from pods already in the cluster (topology.go:274-321)."""
        for pod, node_labels, node_name in self.existing_pods:
            if pod.uid in self.excluded_pods or ignored_for_topology(pod):
                continue
            if pod.metadata.namespace not in group.namespaces:
                continue
            if group.selector is None or not group.selector.matches(
                pod.metadata.labels
            ):
                continue
            domain = node_labels.get(group.key)
            if domain is None and group.key == apilabels.LABEL_HOSTNAME:
                domain = node_name
            if domain is None:
                continue
            labels = dict(node_labels)
            labels.setdefault(apilabels.LABEL_HOSTNAME, node_name)
            if not group.node_filter.matches_labels(labels):
                continue
            group.record(domain)

    # -- solve-time protocol ----------------------------------------------

    def add_requirements(
        self,
        strict_pod_requirements: Requirements,
        node_requirements: Requirements,
        pod: Pod,
        allow_undefined: frozenset = frozenset(),
    ) -> Requirements:
        """Tightening requirements from every group that owns or counts the
        pod; raises TopologyError when any group admits no domain
        (topology.go:160-190)."""
        out = Requirements()
        for group in self._matching_topologies(pod, node_requirements, allow_undefined):
            pod_domains = strict_pod_requirements.get(group.key)
            node_domains = node_requirements.get(group.key)
            domains = group.get(pod, pod_domains, node_domains)
            if domains.length() == 0:
                counts = dict(sorted(group.domains.items())[:8])
                raise TopologyError(
                    f"unsatisfiable topology constraint for {group.type}, "
                    f"key={group.key} (counts = {counts}, "
                    f"podDomains = {pod_domains!r}, nodeDomains = {node_domains!r})"
                )
            out.add(domains)
        return out

    def record(
        self,
        pod: Pod,
        requirements: Requirements,
        allow_undefined: frozenset = frozenset(),
    ) -> None:
        """Commit the placement into every group that cares
        (topology.go:143-158)."""
        for group in self.topologies.values():
            if group.counts(pod, requirements, allow_undefined):
                domains = requirements.get(group.key)
                if group.type == TYPE_ANTI_AFFINITY:
                    # block every domain the pod could land in
                    group.record(*domains.sorted_values())
                elif domains.length() == 1 and not domains.complement:
                    group.record(domains.sorted_values()[0])
        for group in self.inverse_topologies.values():
            if group.is_owned_by(pod.uid):
                group.record(*requirements.get(group.key).sorted_values())

    def register(self, key: str, domain: str) -> None:
        """New in-flight hostname / discovered domain (topology.go:193-205)."""
        self.domains.setdefault(key, set()).add(domain)
        for group in self.topologies.values():
            if group.key == key:
                group.register(domain)
        for group in self.inverse_topologies.values():
            if group.key == key:
                group.register(domain)

    def unregister(self, key: str, domain: str) -> None:
        self.domains.get(key, set()).discard(domain)
        for group in self.topologies.values():
            if group.key == key:
                group.unregister(domain)
        for group in self.inverse_topologies.values():
            if group.key == key:
                group.unregister(domain)

    def _matching_topologies(
        self, pod: Pod, requirements: Requirements, allow_undefined: frozenset
    ) -> List[TopologyGroup]:
        """Groups owning the pod + inverse groups whose selector the pod
        matches (topology.go:400-414)."""
        out = list(self._owned.get(pod.uid, ()))
        out.extend(
            g
            for g in self.inverse_topologies.values()
            if g.counts(pod, requirements, allow_undefined)
        )
        return out


def domain_universe(
    nodepools,
    instance_types: Dict[str, list],
    existing_nodes=(),
) -> Dict[str, Set[str]]:
    """The closed world of topology domains discoverable before a solve.

    Instance-type requirement values are INTERSECTED with the NodePool's
    requirements+labels first so e.g. zones an instance type offers but the
    pool forbids don't expand the universe (provisioner.go:251-283). Existing
    node domains enter via registration/record, not the universe, matching
    the reference (``existing_nodes`` kept for callers that need hostname
    seeding before any group exists)."""
    domains: Dict[str, Set[str]] = {}

    def observe(key: str, values) -> None:
        if values:
            domains.setdefault(key, set()).update(values)

    for pool in nodepools:
        pool_reqs = Requirements.from_node_selector_requirements_with_min_values(
            pool.spec.template.requirements
        )
        pool_reqs.add(
            *Requirements.from_labels(pool.spec.template.labels).values()
        )
        for it in instance_types.get(pool.name, []):
            reqs = pool_reqs.copy()
            reqs.add(*(r.copy() for r in it.requirements.values()))
            for key, req in reqs.items():
                if not req.complement:
                    observe(key, req.values)
        for key, req in pool_reqs.items():
            if req.operator() == OP_IN:
                observe(key, req.values)
    for node in existing_nodes:
        if apilabels.LABEL_HOSTNAME not in node.labels:
            observe(apilabels.LABEL_HOSTNAME, [node.name])
    return domains
