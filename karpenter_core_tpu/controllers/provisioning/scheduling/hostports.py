"""Host-port conflict tracking per hypothesized node
(reference: pkg/scheduling/hostportusage.go:34-90)."""
from __future__ import annotations

from typing import List, Optional, Tuple

from karpenter_core_tpu.api.objects import Pod

HostPort = Tuple[str, int, str]  # (ip, port, protocol)


class HostPortUsage:
    def __init__(self):
        self.reserved: List[Tuple[str, HostPort]] = []  # (pod uid, port)

    def conflicts(self, pod: Pod, ports: List[HostPort]) -> Optional[str]:
        for _, (ip, port, proto) in self.reserved:
            for nip, nport, nproto in ports:
                if port == nport and proto == nproto and (
                    ip == nip or ip == "0.0.0.0" or nip == "0.0.0.0"
                ):
                    return f"host port {nip}:{nport}/{nproto} already in use"
        return None

    def add(self, pod: Pod, ports: List[HostPort]) -> None:
        self.reserved.extend((pod.uid, p) for p in ports)

    def remove(self, pod_uid: str) -> None:
        self.reserved = [(u, p) for u, p in self.reserved if u != pod_uid]
