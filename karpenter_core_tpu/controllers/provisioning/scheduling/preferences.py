"""Preference relaxation — on scheduling failure, progressively drop soft
constraints (reference: pkg/controllers/provisioning/scheduling/preferences.go:32-146).

Order: required node-affinity term (pop OR alternative) → preferred
pod-affinity → preferred pod-anti-affinity → preferred node-affinity →
ScheduleAnyway topology spreads → tolerate PreferNoSchedule taints."""
from __future__ import annotations

from typing import Optional

from karpenter_core_tpu.api.objects import (
    TAINT_EFFECT_PREFER_NO_SCHEDULE,
    TOLERATION_OP_EXISTS,
    Pod,
    Toleration,
)


class Preferences:
    def __init__(self, tolerate_prefer_no_schedule: bool = False):
        self.tolerate_prefer_no_schedule = tolerate_prefer_no_schedule

    def relax(self, pod: Pod) -> bool:
        relaxations = [
            self._remove_required_node_affinity_term,
            self._remove_preferred_pod_affinity_term,
            self._remove_preferred_pod_anti_affinity_term,
            self._remove_preferred_node_affinity_term,
            self._remove_topology_spread_schedule_anyway,
        ]
        if self.tolerate_prefer_no_schedule:
            relaxations.append(self._tolerate_prefer_no_schedule_taints)
        for fn in relaxations:
            reason = fn(pod)
            if reason is not None:
                return True
        return False

    def _remove_required_node_affinity_term(self, pod: Pod) -> Optional[str]:
        na = pod.affinity.node_affinity if pod.affinity else None
        if na is None or len(na.required) <= 1:
            # cannot drop the last required term (preferences.go:76-89)
            return None
        dropped = na.required.pop(0)
        return f"removed required node affinity term {dropped}"

    def _remove_preferred_node_affinity_term(self, pod: Pod) -> Optional[str]:
        na = pod.affinity.node_affinity if pod.affinity else None
        if na is None or not na.preferred:
            return None
        na.preferred.sort(key=lambda t: -t.weight)
        dropped = na.preferred.pop(0)
        return f"removed preferred node affinity term {dropped}"

    def _remove_preferred_pod_affinity_term(self, pod: Pod) -> Optional[str]:
        pa = pod.affinity.pod_affinity if pod.affinity else None
        if pa is None or not pa.preferred:
            return None
        pa.preferred.sort(key=lambda t: -t.weight)
        dropped = pa.preferred.pop(0)
        return f"removed preferred pod affinity term {dropped}"

    def _remove_preferred_pod_anti_affinity_term(self, pod: Pod) -> Optional[str]:
        pa = pod.affinity.pod_anti_affinity if pod.affinity else None
        if pa is None or not pa.preferred:
            return None
        pa.preferred.sort(key=lambda t: -t.weight)
        dropped = pa.preferred.pop(0)
        return f"removed preferred pod anti-affinity term {dropped}"

    def _remove_topology_spread_schedule_anyway(self, pod: Pod) -> Optional[str]:
        for i, tsc in enumerate(pod.topology_spread_constraints):
            if tsc.when_unsatisfiable == "ScheduleAnyway":
                pod.topology_spread_constraints.pop(i)
                return f"removed ScheduleAnyway topology spread {tsc}"
        return None

    def _tolerate_prefer_no_schedule_taints(self, pod: Pod) -> Optional[str]:
        marker = Toleration(
            operator=TOLERATION_OP_EXISTS, effect=TAINT_EFFECT_PREFER_NO_SCHEDULE
        )
        if any(
            t.operator == TOLERATION_OP_EXISTS
            and t.effect == TAINT_EFFECT_PREFER_NO_SCHEDULE
            and not t.key
            for t in pod.tolerations
        ):
            return None
        pod.tolerations.append(marker)
        return "added toleration for PreferNoSchedule taints"
