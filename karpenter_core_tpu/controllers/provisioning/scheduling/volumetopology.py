"""Volume-derived node requirements, stamped onto pods pre-solve
(reference: pkg/controllers/provisioning/scheduling/volumetopology.go:42-166).

The reference ANDs each PVC's zone requirement into EVERY node-selector term
of the pod so relaxation can't strip it (volumetopology.go:68-72). Here the
same invariant holds structurally: ``inject`` stamps
``pod.volume_requirements`` (a flat AND list) and ``Requirements.from_pod``
folds them in unconditionally — preference relaxation only ever touches
``pod.affinity``, so the volume terms survive by construction.
"""
from __future__ import annotations

from typing import List, Optional

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import (
    NodeSelectorRequirement,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    StorageClass,
)
from karpenter_core_tpu.scheduling.volumeusage import pvc_name_for


class VolumeTopology:
    def __init__(self, kube):
        self.kube = kube

    def inject(self, pod: Pod) -> None:
        """Recompute pod.volume_requirements from the pod's PVCs. Idempotent:
        the list is replaced wholesale each call (the reference re-reads pods
        from the apiserver each solve; our store hands out live objects)."""
        requirements: List[NodeSelectorRequirement] = []
        for vol in pod.volumes:
            requirements.extend(self._requirements_for(pod, vol))
        pod.volume_requirements = requirements

    def _requirements_for(self, pod: Pod, vol) -> List[NodeSelectorRequirement]:
        claim_name = pvc_name_for(pod, vol)
        if claim_name is None:
            return []
        pvc = self.kube.get(
            PersistentVolumeClaim, claim_name, pod.metadata.namespace
        )
        if pvc is None:
            return []
        if pvc.volume_name:
            return self._pv_requirements(pvc.volume_name)
        if pvc.storage_class_name:
            return self._storage_class_requirements(pvc.storage_class_name)
        return []

    def _pv_requirements(self, pv_name: str) -> List[NodeSelectorRequirement]:
        """First required term's expressions; local/hostPath volumes drop the
        hostname pin (rescheduling means a different node,
        volumetopology.go:124-148)."""
        pv = self.kube.get(PersistentVolume, pv_name)
        if pv is None or not pv.node_affinity_required:
            return []
        exprs = list(pv.node_affinity_required[0].match_expressions)
        if pv.local or pv.host_path:
            exprs = [e for e in exprs if e.key != apilabels.LABEL_HOSTNAME]
        return exprs

    def _storage_class_requirements(
        self, name: str
    ) -> List[NodeSelectorRequirement]:
        """allowedTopologies[0] as In requirements (volumetopology.go:110-122)."""
        sc = self.kube.get(StorageClass, name)
        if sc is None or not sc.allowed_topologies:
            return []
        return [
            NodeSelectorRequirement(key, "In", tuple(values))
            for key, values in sc.allowed_topologies
        ]

    def validate_pvcs(self, pod: Pod) -> Optional[str]:
        """Error string when the pod references a missing PVC or a dangling
        unbound storage class — such pods are excluded from the solve with
        an event (volumetopology.go:152-196, provisioner.go:436-516)."""
        for vol in pod.volumes:
            claim_name = pvc_name_for(pod, vol)
            if claim_name is None:
                continue
            pvc = self.kube.get(
                PersistentVolumeClaim, claim_name, pod.metadata.namespace
            )
            if pvc is None:
                return f"unbound pvc {claim_name!r} not found"
            if pvc.volume_name:
                if self.kube.get(PersistentVolume, pvc.volume_name) is None:
                    return (
                        f"pvc {claim_name!r} references missing persistent "
                        f"volume {pvc.volume_name!r}"
                    )
            elif pvc.storage_class_name:
                if self.kube.get(StorageClass, pvc.storage_class_name) is None:
                    return (
                        f"pvc {claim_name!r} references missing storage "
                        f"class {pvc.storage_class_name!r}"
                    )
        return None
