"""In-flight scheduling entities: the hypothesized new node (NodeClaim) and
the simulation wrapper for existing nodes
(reference: scheduling/nodeclaim.go:35-148, existingnode.go:31-128)."""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import Pod, Taint
from karpenter_core_tpu.cloudprovider.types import InstanceType
from karpenter_core_tpu.controllers.provisioning.scheduling.hostports import (
    HostPortUsage,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.nodeclaimtemplate import (
    NodeClaimTemplate,
    filter_instance_types,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
    Topology,
    TopologyError,
)
from karpenter_core_tpu.scheduling import Requirement, Requirements, Taints
from karpenter_core_tpu.scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    has_preferred_node_affinity,
)
from karpenter_core_tpu.utils import resources as resutil

_hostname_counter = itertools.count(1)


class IncompatibleError(Exception):
    pass


_MAX_ALLOC_MEMO: dict = {}


def _max_allocatable(instance_types: List[InstanceType]) -> dict:
    """Elementwise max allocatable across options — the roomiest any single
    node from this set could be. Memoized on the option identity tuple;
    the memo value keeps a strong reference to the option objects so their
    ids can't be recycled while the entry lives (bounded, then cleared)."""
    key = tuple(id(it) for it in instance_types)
    hit = _MAX_ALLOC_MEMO.get(key)
    if hit is not None:
        return hit[1]
    out: dict = {}
    for it in instance_types:
        for name, qty in it.allocatable().items():
            if qty > out.get(name, 0.0):
                out[name] = qty
    if len(_MAX_ALLOC_MEMO) > 4096:
        _MAX_ALLOC_MEMO.clear()
    _MAX_ALLOC_MEMO[key] = (tuple(instance_types), out)
    return out


class InFlightNodeClaim:
    """A node being hypothesized during the solve (nodeclaim.go:35-64)."""

    def __init__(
        self,
        template: NodeClaimTemplate,
        topology: Topology,
        daemon_resources: dict,
        instance_types: List[InstanceType],
    ):
        self.template = template
        self.hostname = f"hostname-placeholder-{next(_hostname_counter):04d}"
        topology.register(apilabels.LABEL_HOSTNAME, self.hostname)
        self.requirements = template.requirements.copy()
        self.requirements.add(
            Requirement.new(apilabels.LABEL_HOSTNAME, "In", [self.hostname])
        )
        self.instance_type_options = list(instance_types)
        self.daemon_resources = dict(daemon_resources)
        self.requests = dict(daemon_resources)
        self.pods: List[Pod] = []
        self.topology = topology
        self.host_port_usage = HostPortUsage()
        self._max_alloc_cache: Optional[dict] = None

    def add(self, pod: Pod, pod_requests: dict) -> None:
        """Raises IncompatibleError when the pod cannot join (nodeclaim.go:67-122)."""
        errs = Taints(self.template.taints).tolerates(pod)
        if errs:
            raise IncompatibleError("; ".join(errs))

        conflict = self.host_port_usage.conflicts(pod, pod.host_ports)
        if conflict:
            raise IncompatibleError(conflict)

        # cheap reject before any requirement copying: if the cumulative
        # requests exceed even the roomiest remaining option, no instance
        # type can fit (dominates when a fallback pod scans many claims)
        requests = resutil.merge(self.requests, pod_requests)
        if not resutil.fits(requests, self._max_alloc()):
            raise IncompatibleError("no instance type has enough resources")

        claim_requirements = self.requirements.copy()
        pod_requirements = Requirements.from_pod(pod)
        errs = claim_requirements.compatible(
            pod_requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
        )
        if errs:
            raise IncompatibleError(f"incompatible requirements, {errs}")
        claim_requirements.add(*pod_requirements.values())

        strict = (
            Requirements.from_pod_strict(pod)
            if has_preferred_node_affinity(pod)
            else pod_requirements
        )
        try:
            topology_requirements = self.topology.add_requirements(
                strict, claim_requirements, pod, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
            )
        except TopologyError as e:
            raise IncompatibleError(str(e))
        errs = claim_requirements.compatible(
            topology_requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
        )
        if errs:
            raise IncompatibleError(f"incompatible topology, {errs}")
        claim_requirements.add(*topology_requirements.values())
        filtered = filter_instance_types(
            self.instance_type_options, claim_requirements, requests
        )
        if not filtered.remaining:
            total = resutil.merge(self.daemon_resources, pod_requests)
            raise IncompatibleError(
                f"no instance type satisfied resources {resutil.to_string(total)} "
                f"and requirements ({filtered.failure_reason()})"
            )

        self.pods.append(pod)
        if len(filtered.remaining) != len(self.instance_type_options):
            self._max_alloc_cache = None
        self.instance_type_options = filtered.remaining
        self.requests = requests
        self.requirements = claim_requirements
        self.topology.record(pod, claim_requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)
        self.host_port_usage.add(pod, pod.host_ports)

    def _max_alloc(self) -> dict:
        if self._max_alloc_cache is None:
            self._max_alloc_cache = _max_allocatable(self.instance_type_options)
        return self._max_alloc_cache

    def add_group(self, pods: List[Pod], per_pod_requests: dict) -> None:
        """Batch-add k IDENTICAL pods in one pass of the host algebra.

        Equivalent to k sequential add() calls when (a) the pods share one
        spec (same requirements/tolerations/requests — a solver equivalence
        class), (b) no topology groups are active, and (c) no host ports:
        the requirement intersection is idempotent after the first add and
        resource narrowing is monotone, so one filter at the cumulative
        requests equals the k-th sequential filter. The decode path guards
        those preconditions and falls back to per-pod adds otherwise."""
        pod = pods[0]
        errs = Taints(self.template.taints).tolerates(pod)
        if errs:
            raise IncompatibleError("; ".join(errs))

        claim_requirements = self.requirements.copy()
        pod_requirements = Requirements.from_pod(pod)
        errs = claim_requirements.compatible(
            pod_requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
        )
        if errs:
            raise IncompatibleError(f"incompatible requirements, {errs}")
        claim_requirements.add(*pod_requirements.values())

        requests = resutil.merge_repeated(
            self.requests, per_pod_requests, len(pods)
        )
        if not resutil.fits(requests, self._max_alloc()):
            raise IncompatibleError("no instance type has enough resources")
        filtered = filter_instance_types(
            self.instance_type_options, claim_requirements, requests
        )
        if not filtered.remaining:
            total = resutil.merge(self.daemon_resources, per_pod_requests)
            raise IncompatibleError(
                f"no instance type satisfied resources {resutil.to_string(total)}"
                f" x{len(pods)} and requirements ({filtered.failure_reason()})"
            )

        self.pods.extend(pods)
        if len(filtered.remaining) != len(self.instance_type_options):
            self._max_alloc_cache = None
        self.instance_type_options = filtered.remaining
        self.requests = requests
        self.requirements = claim_requirements

    def destroy(self) -> None:
        self.topology.unregister(apilabels.LABEL_HOSTNAME, self.hostname)

    def finalize_scheduling(self) -> None:
        """Remove the placeholder hostname before launch (nodeclaim.go:139-148)."""
        self.requirements.pop(apilabels.LABEL_HOSTNAME, None)


@dataclass(frozen=True)
class EvictablePod:
    """One bound pod a preemptive solve may evict (gangsched, ISSUE 10).

    A capacity view, not an API object: uid names the victim for the
    eviction claim, requests is the capacity its eviction frees, priority
    feeds the tier-legality rule (only strictly-lower tiers are evictable,
    utils/disruption.priority_tier), and cost is the victim-selection
    ordering (utils/disruption.eviction_cost, computed by whoever builds
    the SimNode — the kernel and the host fallback both sort by it)."""

    uid: str
    priority: int
    requests: dict
    cost: float


@dataclass
class SimNode:
    """Minimal view of an existing/in-flight real node for simulation; the
    cluster-state layer constructs these from StateNodes."""

    name: str
    labels: dict
    taints: List[Taint]
    available: dict  # allocatable minus bound pods (statenode.go:329-366)
    capacity: dict = field(default_factory=dict)
    daemon_requests: dict = field(default_factory=dict)
    initialized: bool = True
    nodeclaim_name: str = ""
    nodepool_name: str = ""
    # CSI attach-limit state (volumeusage.go): filled by the provisioner
    # from the node's CSINode + bound pods; None = no volume tracking
    volume_usage: Optional[object] = None
    # bound pods a priority-preemptive solve may treat as evictable
    # capacity (ops/gangsched.preempt_pass); empty = nothing evictable,
    # which is also the pre-gangsched wire default
    evictable: tuple = ()


class ExistingNodeSim:
    """Existing-node wrapper with daemon overhead floored at zero
    (existingnode.go:42-128)."""

    def __init__(self, node: SimNode, topology: Topology, daemon_resources: dict):
        remaining = resutil.subtract(daemon_resources, node.daemon_requests)
        for k in list(remaining):
            if remaining[k] < 0:
                remaining[k] = 0.0
        self.node = node
        self.cached_available = dict(node.available)
        self.cached_taints = list(node.taints)
        self.pods: List[Pod] = []
        self.topology = topology
        self.requests = remaining
        self.requirements = Requirements.from_labels(node.labels)
        self.requirements.add(
            Requirement.new(apilabels.LABEL_HOSTNAME, "In", [node.name])
        )
        topology.register(apilabels.LABEL_HOSTNAME, node.name)
        self.host_port_usage = HostPortUsage()
        # per-sim copy: hypothesized placements must not leak into the
        # node's baseline usage across solves/relaxation rounds
        self.volume_usage = (
            node.volume_usage.copy() if node.volume_usage is not None else None
        )

    @property
    def name(self) -> str:
        return self.node.name

    def add(self, pod: Pod, pod_requests: dict) -> None:
        errs = Taints(self.cached_taints).tolerates(pod)
        if errs:
            raise IncompatibleError("; ".join(errs))

        conflict = self.host_port_usage.conflicts(pod, pod.host_ports)
        if conflict:
            raise IncompatibleError(conflict)

        err = self._volume_limit_error([pod])
        if err:
            raise IncompatibleError(err)

        requests = resutil.merge(self.requests, pod_requests)
        if not resutil.fits(requests, self.cached_available):
            raise IncompatibleError("exceeds node resources")

        node_requirements = self.requirements.copy()
        pod_requirements = Requirements.from_pod(pod)
        errs = node_requirements.compatible(pod_requirements)
        if errs:
            raise IncompatibleError(f"incompatible requirements, {errs}")
        node_requirements.add(*pod_requirements.values())

        strict = (
            Requirements.from_pod_strict(pod)
            if has_preferred_node_affinity(pod)
            else pod_requirements
        )
        try:
            topology_requirements = self.topology.add_requirements(
                strict, node_requirements, pod
            )
        except TopologyError as e:
            raise IncompatibleError(str(e))
        errs = node_requirements.compatible(topology_requirements)
        if errs:
            raise IncompatibleError(f"incompatible topology, {errs}")
        node_requirements.add(*topology_requirements.values())

        self.pods.append(pod)
        self.requests = requests
        self.requirements = node_requirements
        self.topology.record(pod, node_requirements)
        self.host_port_usage.add(pod, pod.host_ports)
        self._record_volumes([pod])

    def add_group(self, pods: List[Pod], per_pod_requests: dict) -> None:
        """Batch-add k identical pods; same preconditions as
        InFlightNodeClaim.add_group."""
        pod = pods[0]
        errs = Taints(self.cached_taints).tolerates(pod)
        if errs:
            raise IncompatibleError("; ".join(errs))

        err = self._volume_limit_error(pods)
        if err:
            raise IncompatibleError(err)

        requests = resutil.merge_repeated(
            self.requests, per_pod_requests, len(pods)
        )
        if not resutil.fits(requests, self.cached_available):
            raise IncompatibleError("exceeds node resources")

        node_requirements = self.requirements.copy()
        pod_requirements = Requirements.from_pod(pod)
        errs = node_requirements.compatible(pod_requirements)
        if errs:
            raise IncompatibleError(f"incompatible requirements, {errs}")
        node_requirements.add(*pod_requirements.values())

        self.pods.extend(pods)
        self.requests = requests
        self.requirements = node_requirements
        self._record_volumes(pods)

    # -- CSI attach limits (existingnode.go:84-90; new claims have no
    # CSINode yet so only existing nodes enforce them) --------------------

    def _pods_volumes(self, pods: List[Pod]) -> Optional[dict]:
        from karpenter_core_tpu.scheduling import volumeusage as vu

        joined: dict = {}
        for p in pods:
            if p.resolved_volumes:
                joined = vu.union(joined, p.resolved_volumes)
        return joined or None

    def _volume_limit_error(self, pods: List[Pod]) -> Optional[str]:
        if self.volume_usage is None:
            return None
        vols = self._pods_volumes(pods)
        if vols is None:
            return None
        return self.volume_usage.exceeds_limits(vols)

    def _record_volumes(self, pods: List[Pod]) -> None:
        if self.volume_usage is None:
            return
        vols = self._pods_volumes(pods)
        if vols is not None:
            self.volume_usage.add(vols)
