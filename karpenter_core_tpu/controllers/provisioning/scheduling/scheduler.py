"""The greedy host scheduler — reference-semantics FFD loop
(reference: scheduling/scheduler.go:47-316).

This is both the fallback scheduling path (``--solver=greedy``) and the
parity oracle the TPU solver (models/provisioner.py) is differential-tested
against: identical inputs must produce node-count parity and zero constraint
violations.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_core_tpu.api.nodepool import NodePool
from karpenter_core_tpu.api.objects import Pod
from karpenter_core_tpu.cloudprovider.types import InstanceType
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
    ExistingNodeSim,
    IncompatibleError,
    InFlightNodeClaim,
    SimNode,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.nodeclaimtemplate import (
    NodeClaimTemplate,
    filter_instance_types,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.preferences import (
    Preferences,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.queue import (
    Queue,
    by_cpu_and_memory_descending,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
    Topology,
    domain_universe,
)
from karpenter_core_tpu.scheduling import Requirements, Taints
from karpenter_core_tpu.scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
)
from karpenter_core_tpu.utils import resources as resutil


@dataclass
class Results:
    """Solve output (scheduler.go:109-206)."""

    new_node_claims: List[InFlightNodeClaim]
    existing_nodes: List[ExistingNodeSim]
    pod_errors: Dict[str, str]  # pod uid -> error
    # eviction claims (gangsched, ISSUE 10): node name -> bound-pod uids a
    # preemptive solve selected as victims. The placements on that node
    # assume the freed capacity, so the operator drains these BEFORE
    # binding (drain-before-bind); empty for every non-preemptive solve,
    # which is also the byte-parity wire default
    evictions: Dict[str, List[str]] = field(default_factory=dict)

    def all_pods_scheduled(self) -> bool:
        return not self.pod_errors

    def node_count(self) -> int:
        return len(self.new_node_claims)

    def total_price(self) -> float:
        total = 0.0
        for claim in self.new_node_claims:
            cheapest = min(
                (
                    o.price
                    for it in claim.instance_type_options
                    for o in it.offerings.available().compatible(claim.requirements)
                ),
                default=0.0,
            )
            total += cheapest
        return total


class Scheduler:
    def __init__(
        self,
        nodepools: List[NodePool],
        instance_types: Dict[str, List[InstanceType]],
        existing_nodes: Optional[List[SimNode]] = None,
        daemonset_pods: Optional[List[Pod]] = None,
        topology: Optional[Topology] = None,
        unavailable_offerings: "frozenset | set" = frozenset(),
    ):
        # ICE'd offerings (the UnavailableOfferings snapshot) project onto
        # the catalog before anything consults availability: the per-
        # template prefilter, in-flight offering narrowing, and price
        # ordering all see the stockout and pack onto the next-cheapest
        # AVAILABLE offering (cloudprovider/types.py apply_unavailable)
        from karpenter_core_tpu.cloudprovider.types import apply_unavailable

        instance_types = apply_unavailable(instance_types, unavailable_offerings)
        self.unavailable_offerings = frozenset(unavailable_offerings)
        # default topology over the discoverable domain universe
        # (provisioner.go:251-283); the provisioning controller passes a
        # Topology seeded with live cluster pods instead
        self.topology = topology or Topology(
            domains=domain_universe(nodepools, instance_types, existing_nodes or [])
        )
        daemonset_pods = daemonset_pods or []

        tolerate_prefer_no_schedule = any(
            t.effect == "PreferNoSchedule"
            for np in nodepools
            for t in np.spec.template.taints
        )
        self.preferences = Preferences(tolerate_prefer_no_schedule)

        # Pre-filter instance types per template (scheduler.go:63-72);
        # nodepools are iterated in weight order (provisioner.go:215-234).
        self.templates: List[NodeClaimTemplate] = []
        for np in sorted(nodepools, key=lambda n: (-n.spec.weight, n.name)):
            nct = NodeClaimTemplate.from_nodepool(np)
            nct.instance_type_options = filter_instance_types(
                instance_types.get(np.name, []), nct.requirements, {}
            ).remaining
            if nct.instance_type_options:
                self.templates.append(nct)

        # NodePool resource limits minus existing usage (scheduler.go:85-88)
        self.remaining_resources: Dict[str, dict] = {
            np.name: dict(np.spec.limits) for np in nodepools if np.spec.limits
        }

        # daemon overhead per template (scheduler.go:358-364)
        self.daemon_overhead = {
            id(nct): resutil.requests_for_pods(
                *[p for p in daemonset_pods if _daemon_compatible(nct, p)]
            )
            for nct in self.templates
        }

        self.new_node_claims: List[InFlightNodeClaim] = []
        self.existing_nodes: List[ExistingNodeSim] = []
        self.cached_pod_requests: Dict[str, dict] = {}
        self._build_existing(existing_nodes or [], daemonset_pods)

    def _build_existing(self, nodes: List[SimNode], daemonset_pods: List[Pod]):
        """(scheduler.go:318-354)"""
        for node in nodes:
            daemons = node_daemon_pods(node, daemonset_pods)
            self.existing_nodes.append(
                ExistingNodeSim(
                    node, self.topology, resutil.requests_for_pods(*daemons)
                )
            )
            if node.nodepool_name in self.remaining_resources:
                # recompute remaining against live capacity (scheduler.go:336-340)
                self.remaining_resources[node.nodepool_name] = resutil.subtract(
                    self.remaining_resources[node.nodepool_name],
                    node.capacity or node.available,
                )
        # initialized nodes first, then by name (scheduler.go:344-354)
        self.existing_nodes.sort(key=lambda n: (not n.node.initialized, n.name))

    def solve(self, pods: List[Pod]) -> Results:
        """The FFD loop (scheduler.go:208-266)."""
        errors: Dict[str, str] = {}
        self.topology.ensure_inverse_initialized()
        for p in pods:
            self.cached_pod_requests[p.uid] = resutil.requests_for_pods(p)
            # NewTopology registers every solve pod; constraint-free pods
            # build no groups so the call is skipped on the 50k path
            if p.topology_spread_constraints or p.affinity is not None:
                self.topology.update(p)
        q = Queue(pods, self.cached_pod_requests)
        pods_by_uid = {p.uid: p for p in pods}

        while True:
            pod, ok = q.pop()
            if not ok:
                break
            err = self._add(pod)
            if err is None:
                errors.pop(pod.uid, None)
                continue
            errors[pod.uid] = err
            relaxed = self.preferences.relax(pod)
            q.push(pod, relaxed)
            if relaxed:
                self.topology.update(pod)

        for claim in self.new_node_claims:
            claim.finalize_scheduling()
        return Results(
            new_node_claims=self.new_node_claims,
            existing_nodes=self.existing_nodes,
            pod_errors=errors,
        )

    def _add(self, pod: Pod) -> Optional[str]:
        return place_pod(
            pod,
            self.cached_pod_requests[pod.uid],
            self.existing_nodes,
            self.new_node_claims,
            self.templates,
            self.daemon_overhead,
            self.topology,
            self.remaining_resources,
        )


def place_pod(
    pod: Pod,
    pod_requests: dict,
    existing_nodes: List[ExistingNodeSim],
    claims: List[InFlightNodeClaim],
    templates: List[NodeClaimTemplate],
    daemon_overhead: Dict[int, dict],  # id(template) -> resources
    topology: Topology,
    remaining_resources: Dict[str, dict],  # nodepool -> remaining; mutated
) -> Optional[str]:
    """The single-pod placement policy (scheduler.go:268-316): existing real
    nodes, then in-flight claims emptiest first, then a fresh claim from the
    first workable template. Shared by the greedy loop and the device
    solver's host fallback so the order/limit policy cannot diverge."""
    for node in existing_nodes:
        try:
            node.add(pod, pod_requests)
            return None
        except IncompatibleError:
            continue

    claims.sort(key=lambda c: len(c.pods))
    for claim in claims:
        try:
            claim.add(pod, pod_requests)
            return None
        except IncompatibleError:
            continue

    errs = []
    for template in templates:
        instance_types = template.instance_type_options
        remaining = remaining_resources.get(template.nodepool_name)
        if remaining is not None:
            instance_types = _filter_by_remaining_resources(
                instance_types, remaining
            )
            if not instance_types:
                errs.append(
                    f"all available instance types exceed limits for "
                    f"nodepool {template.nodepool_name!r}"
                )
                continue
        claim = InFlightNodeClaim(
            template,
            topology,
            daemon_overhead.get(id(template), {}),
            instance_types,
        )
        try:
            claim.add(pod, pod_requests)
        except IncompatibleError as e:
            claim.destroy()
            errs.append(f"incompatible with nodepool {template.nodepool_name!r}: {e}")
            continue
        claims.append(claim)
        if remaining is not None:
            remaining_resources[template.nodepool_name] = _subtract_max(
                remaining, claim.instance_type_options
            )
        return None
    return "; ".join(errs) or "no nodepool matched pod"


def node_daemon_pods(node: SimNode, daemonset_pods: List[Pod]) -> List[Pod]:
    """Daemonset pods that would land on this node: tolerate its taints and
    match its labels (scheduler.go:320-332)."""
    daemons = []
    for p in daemonset_pods:
        if Taints(node.taints).tolerates(p):
            continue
        if Requirements.from_labels(node.labels).compatible(
            Requirements.from_pod(p)
        ):
            continue
        daemons.append(p)
    return daemons


def _daemon_compatible(template: NodeClaimTemplate, pod: Pod) -> bool:
    """(scheduler.go:366-386) — daemons tolerate PreferNoSchedule, relax
    required node-affinity terms one at a time."""
    import copy

    pod = copy.deepcopy(pod)
    prefs = Preferences()
    prefs._tolerate_prefer_no_schedule_taints(pod)
    if Taints(template.taints).tolerates(pod):
        return False
    while True:
        if template.requirements.is_compatible(
            Requirements.from_pod_strict(pod), ALLOW_UNDEFINED_WELL_KNOWN_LABELS
        ):
            return True
        if prefs._remove_required_node_affinity_term(pod) is None:
            return False


def _filter_by_remaining_resources(instance_types, remaining) -> list:
    """Drop instance types whose capacity would breach NodePool limits
    (scheduler.go:417-434)."""
    out = []
    for it in instance_types:
        if all(
            it.capacity.get(name, 0.0) <= qty for name, qty in remaining.items()
        ):
            out.append(it)
    return out


def _subtract_max(remaining: dict, instance_types) -> dict:
    """Pessimistically subtract the max capacity over the claim's viable
    instance types (scheduler.go:389-409)."""
    if not instance_types:
        return remaining
    max_caps = resutil.cmp_max(*(it.capacity for it in instance_types))
    return {
        name: qty - max_caps.get(name, 0.0) for name, qty in remaining.items()
    }
