"""Disruption candidates and commands (reference: pkg/controllers/disruption/
types.go:48-141)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodeclaim import COND_CONSOLIDATABLE, COND_DRIFTED
from karpenter_core_tpu.api.objects import Pod
from karpenter_core_tpu.cloudprovider.types import InstanceType, Offering
from karpenter_core_tpu.scheduling import Requirements
from karpenter_core_tpu.utils import disruption as disutil
from karpenter_core_tpu.utils import pod as podutil


class CandidateError(Exception):
    """This node cannot be a disruption candidate (types.go:71-117 gates)."""


@dataclass
class Candidate:
    """A disruptable node with its pricing/cost features (types.go:60-117)."""

    state_node: object  # state.StateNode
    node_claim: object
    nodepool: object
    instance_type: Optional[InstanceType]
    zone: str
    capacity_type: str
    reschedulable_pods: List[Pod]
    disruption_cost: float

    @property
    def name(self) -> str:
        return self.state_node.name

    def price(self) -> float:
        """The candidate's current offering price (consolidation.go
        getCandidatePrices)."""
        if self.instance_type is None:
            return 0.0
        labels = Requirements.from_labels(self.state_node.labels)
        offs = self.instance_type.offerings.available().compatible(labels)
        cheapest: Optional[Offering] = offs.cheapest()
        return cheapest.price if cheapest is not None else 0.0


def new_candidate(
    clock,
    cluster,
    state_node,
    nodepools: dict,
    instance_types_by_pool: dict,
    pdb_limits=None,
) -> Candidate:
    """Construction gates (types.go:71-117): managed, initialized,
    non-deleting, non-nominated, known pool + instance type, disruptable
    pods. Raises CandidateError when any gate fails."""
    claim = state_node.node_claim
    if claim is None or state_node.node is None:
        raise CandidateError("not managed by a NodeClaim")
    if state_node.deleting() or state_node.marked_for_deletion:
        raise CandidateError("already deleting")
    if not state_node.initialized():
        raise CandidateError("not initialized")
    if state_node.nominated(clock.now()):
        raise CandidateError("nominated for pods")
    if (
        state_node.node.metadata.annotations.get(
            apilabels.DO_NOT_DISRUPT_ANNOTATION_KEY
        )
        == "true"
    ):
        raise CandidateError("node has do-not-disrupt annotation")
    pool = nodepools.get(state_node.nodepool_name)
    if pool is None:
        raise CandidateError(f"nodepool {state_node.nodepool_name!r} not found")
    pods = cluster.pods_on_node(state_node.name)
    for p in pods:
        if not podutil.is_disruptable(p):
            raise CandidateError(
                f"pod {p.name} has do-not-disrupt annotation"
            )
    if pdb_limits is not None:
        err = pdb_limits.can_evict_pods(pods)
        if err:
            raise CandidateError(err)
    it_name = state_node.labels.get(apilabels.LABEL_INSTANCE_TYPE, "")
    instance_type = next(
        (
            it
            for it in instance_types_by_pool.get(pool.name, [])
            if it.name == it_name
        ),
        None,
    )
    reschedulable = [p for p in pods if podutil.is_reschedulable(p)]
    cost = disutil.rescheduling_cost(reschedulable) * disutil.lifetime_remaining(
        clock, pool, claim
    )
    return Candidate(
        state_node=state_node,
        node_claim=claim,
        nodepool=pool,
        instance_type=instance_type,
        zone=state_node.labels.get(apilabels.LABEL_TOPOLOGY_ZONE, ""),
        capacity_type=state_node.labels.get(
            apilabels.CAPACITY_TYPE_LABEL_KEY, ""
        ),
        reschedulable_pods=reschedulable,
        disruption_cost=cost,
    )


def is_consolidatable(candidate: Candidate) -> bool:
    return candidate.node_claim.conditions.is_true(COND_CONSOLIDATABLE)


def is_drifted(candidate: Candidate) -> bool:
    return candidate.node_claim.conditions.is_true(COND_DRIFTED)


@dataclass
class Command:
    """candidates to delete + optional replacements (types.go:119-141)."""

    candidates: List[Candidate] = field(default_factory=list)
    replacements: list = field(default_factory=list)  # InFlightNodeClaim
    reason: str = ""

    @property
    def decision(self) -> str:
        if self.candidates and self.replacements:
            return "replace"
        if self.candidates:
            return "delete"
        return "no-op"
