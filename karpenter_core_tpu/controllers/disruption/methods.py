"""Disruption methods: Emptiness, Drift, Single/Multi-node consolidation
(reference: pkg/controllers/disruption/{emptiness,drift,consolidation,
singlenodeconsolidation,multinodeconsolidation}.go).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodepool import (
    REASON_DRIFTED,
    REASON_EMPTY,
    REASON_UNDERUTILIZED,
)
from karpenter_core_tpu.controllers.disruption.helpers import (
    BudgetMapping,
    simulate_scheduling,
)
from karpenter_core_tpu.controllers.disruption.types import (
    Candidate,
    Command,
    is_consolidatable,
    is_drifted,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.nodeclaimtemplate import (
    filter_instance_types,
)
from karpenter_core_tpu.cloudprovider.types import order_by_price, satisfies_min_values
from karpenter_core_tpu.scheduling import Requirement

MULTI_NODE_CONSOLIDATION_CANDIDATE_CAP = 100  # multinodeconsolidation.go:81
MIN_INSTANCE_TYPES_FOR_SPOT_TO_SPOT = 15  # consolidation.go:48-49


def filter_replacement_by_price(claim, max_price: float) -> None:
    """RemoveInstanceTypeOptionsByPriceAndMinValues (nodeclaim.go:136-145):
    keep instance types whose worst launch price under the claim's
    requirements is strictly cheaper than max_price; then re-check
    minValues. Mutates the in-flight claim's options."""
    kept = [
        it
        for it in claim.instance_type_options
        if 0.0
        < it.offerings.available().compatible(claim.requirements).worst_launch_price(
            claim.requirements
        )
        < max_price
    ]
    if claim.requirements.has_min_values():
        _, err = satisfies_min_values(kept, claim.requirements)
        if err is not None:
            kept = []
    claim.instance_type_options = kept


class Emptiness:
    """Zero reschedulable pods + Consolidatable: delete, no simulation
    (emptiness.go:44-122)."""

    reason = REASON_EMPTY
    consolidation_type = "empty"
    validation = "emptiness"  # TTL re-check: still empty (emptiness.go:94-122)

    def __init__(self, ctx):
        self.ctx = ctx

    def should_disrupt(self, c: Candidate) -> bool:
        if c.nodepool.spec.disruption.consolidate_after.is_never:
            return False
        return not c.reschedulable_pods and is_consolidatable(c)

    def compute_command(
        self, budgets: BudgetMapping, candidates: List[Candidate]
    ) -> Command:
        fits = []
        for c in sorted(candidates, key=lambda c: c.disruption_cost):
            if budgets.remaining(c.nodepool.name, self.reason) > 0:
                budgets.consume(c.nodepool.name, self.reason)
                fits.append(c)
        return Command(candidates=fits, reason=self.reason)


class Drift:
    """Drifted condition, oldest first; empties free, others must fully
    reschedule (drift.go:54-115)."""

    reason = REASON_DRIFTED
    consolidation_type = "drift"
    validation = None  # drift executes without a TTL window (drift.go)

    def __init__(self, ctx):
        self.ctx = ctx

    def should_disrupt(self, c: Candidate) -> bool:
        return is_drifted(c)

    def compute_command(
        self, budgets: BudgetMapping, candidates: List[Candidate]
    ) -> Command:
        def drift_time(c: Candidate) -> float:
            cond = c.node_claim.conditions.get("Drifted")
            return cond.last_transition_time if cond else 0.0

        candidates = sorted(candidates, key=drift_time)
        # empty drifted candidates batch together, consuming budget as the
        # batch builds (drift.go:66-80)
        empty = []
        for c in candidates:
            if c.reschedulable_pods:
                continue
            if budgets.remaining(c.nodepool.name, self.reason) > 0:
                budgets.consume(c.nodepool.name, self.reason)
                empty.append(c)
        if empty:
            return Command(candidates=empty, reason=self.reason)
        allowed = [
            c
            for c in candidates
            if budgets.remaining(c.nodepool.name, self.reason) > 0
        ]
        for c in allowed:
            results = simulate_scheduling(
                self.ctx.provisioner, self.ctx.cluster, [c]
            )
            if not results.all_pods_scheduled():
                continue
            budgets.consume(c.nodepool.name, self.reason)
            return Command(
                candidates=[c],
                replacements=results.new_node_claims,
                reason=self.reason,
            )
        return Command()


class _ConsolidationBase:
    """Shared simulate→price-filter pipeline (consolidation.go:133-304)."""

    reason = REASON_UNDERUTILIZED
    validation = "consolidation"  # 15s TTL re-simulation (validation.go)

    def __init__(self, ctx):
        self.ctx = ctx

    def should_disrupt(self, c: Candidate) -> bool:
        if c.instance_type is None:
            return False
        if apilabels.CAPACITY_TYPE_LABEL_KEY not in c.state_node.labels:
            return False
        if apilabels.LABEL_TOPOLOGY_ZONE not in c.state_node.labels:
            return False
        if c.nodepool.spec.disruption.consolidation_policy == "WhenEmpty":
            return not c.reschedulable_pods and is_consolidatable(c)
        return is_consolidatable(c)

    def compute_consolidation(
        self, candidates: List[Candidate]
    ) -> Tuple[Command, object]:
        """(consolidation.go:133-230)"""
        results = simulate_scheduling(
            self.ctx.provisioner, self.ctx.cluster, candidates
        )
        if not results.all_pods_scheduled():
            return Command(), results
        if len(results.new_node_claims) == 0:
            return Command(candidates=candidates, reason=self.reason), results
        if len(results.new_node_claims) != 1:
            return Command(), results

        replacement = results.new_node_claims[0]
        candidate_price = sum(c.price() for c in candidates)
        all_spot = all(
            c.capacity_type == apilabels.CAPACITY_TYPE_SPOT for c in candidates
        )
        replacement.instance_type_options = order_by_price(
            replacement.instance_type_options, replacement.requirements
        )

        ct_req = replacement.requirements.get(apilabels.CAPACITY_TYPE_LABEL_KEY)
        if all_spot and ct_req.has(apilabels.CAPACITY_TYPE_SPOT):
            return self._spot_to_spot(candidates, results, candidate_price)

        filter_replacement_by_price(replacement, candidate_price)
        if not replacement.instance_type_options:
            return Command(), results

        # OD -> [OD, spot]: force spot so insufficient spot capacity fails the
        # launch instead of replacing with pricier on-demand
        # (consolidation.go:211-218)
        if ct_req.has(apilabels.CAPACITY_TYPE_SPOT) and ct_req.has(
            apilabels.CAPACITY_TYPE_ON_DEMAND
        ):
            replacement.requirements.add(
                Requirement.new(
                    apilabels.CAPACITY_TYPE_LABEL_KEY,
                    "In",
                    [apilabels.CAPACITY_TYPE_SPOT],
                )
            )
        return (
            Command(
                candidates=candidates,
                replacements=[replacement],
                reason=self.reason,
            ),
            results,
        )

    def _spot_to_spot(
        self, candidates: List[Candidate], results, candidate_price: float
    ) -> Tuple[Command, object]:
        """(consolidation.go:226-304)"""
        if not self.ctx.feature_gates.get("SpotToSpotConsolidation", False):
            return Command(), results
        replacement = results.new_node_claims[0]
        replacement.requirements.add(
            Requirement.new(
                apilabels.CAPACITY_TYPE_LABEL_KEY,
                "In",
                [apilabels.CAPACITY_TYPE_SPOT],
            )
        )
        replacement.instance_type_options = filter_instance_types(
            replacement.instance_type_options, replacement.requirements, {}
        ).remaining
        filter_replacement_by_price(replacement, candidate_price)
        if not replacement.instance_type_options:
            return Command(), results
        if len(candidates) > 1:
            return (
                Command(
                    candidates=candidates,
                    replacements=[replacement],
                    reason=self.reason,
                ),
                results,
            )
        # single-node: require 15 cheaper options, truncate to 15 so the
        # launched type stays inside the set (no consolidation churn)
        if len(replacement.instance_type_options) < MIN_INSTANCE_TYPES_FOR_SPOT_TO_SPOT:
            return Command(), results
        cap = MIN_INSTANCE_TYPES_FOR_SPOT_TO_SPOT
        if replacement.requirements.has_min_values():
            n, _ = satisfies_min_values(
                replacement.instance_type_options, replacement.requirements
            )
            cap = max(cap, n or 0)
        replacement.instance_type_options = replacement.instance_type_options[:cap]
        return (
            Command(
                candidates=candidates,
                replacements=[replacement],
                reason=self.reason,
            ),
            results,
        )

    def _budget_filter(
        self, budgets: BudgetMapping, candidates: List[Candidate]
    ) -> List[Candidate]:
        out = []
        used: Dict[str, int] = {}
        for c in candidates:
            pool = c.nodepool.name
            if budgets.remaining(pool, self.reason) - used.get(pool, 0) > 0:
                used[pool] = used.get(pool, 0) + 1
                out.append(c)
        return out


# singlenodeconsolidation.go:30 — per-poll budget on host simulations
SINGLE_NODE_CONSOLIDATION_TIMEOUT = 3 * 60.0


class SingleNodeConsolidation(_ConsolidationBase):
    """One candidate at a time, bounded per poll
    (singlenodeconsolidation.go:29-101): a 3-minute wall-clock budget stops
    the sweep mid-list, and a persistent resume cursor rotates the starting
    candidate across polls so the tail of a large cluster is eventually
    evaluated instead of being starved behind the same cheap prefix.

    The cursor is a STABLE KEY — (candidate name, disruption cost) of the
    next candidate to evaluate — not an index: the candidate list is
    re-collected and re-sorted every poll, so under churn an index silently
    points at a different node and the tail can be starved forever. If the
    named candidate is gone by the next poll, the sweep resumes at the
    first candidate at or past the remembered cost (the list is
    cost-sorted), preserving round-robin progress through the tail."""

    consolidation_type = "single"

    def __init__(self, ctx):
        super().__init__(ctx)
        self._resume_key: Optional[Tuple[str, float]] = None

    def _resume_index(self, candidates: List[Candidate]) -> int:
        if self._resume_key is None:
            return 0
        name, cost = self._resume_key
        for i, c in enumerate(candidates):
            if c.name == name:
                return i
        for i, c in enumerate(candidates):
            if c.disruption_cost >= cost:
                return i
        return 0

    def compute_command(
        self, budgets: BudgetMapping, candidates: List[Candidate]
    ) -> Command:
        from karpenter_core_tpu.metrics import wiring as m

        candidates = self._budget_filter(
            budgets, sorted(candidates, key=lambda c: c.disruption_cost)
        )
        if not candidates:
            return Command()
        start = self._resume_index(candidates)
        rotated = candidates[start:] + candidates[:start]
        deadline = self.ctx.clock.now() + SINGLE_NODE_CONSOLIDATION_TIMEOUT

        def remember(idx: int) -> None:
            nxt = rotated[idx % len(rotated)]
            self._resume_key = (nxt.name, nxt.disruption_cost)

        for i, c in enumerate(rotated):
            if self.ctx.clock.now() > deadline:
                m.CONSOLIDATION_TIMEOUTS.inc(
                    {"consolidation_type": self.consolidation_type}
                )
                # resume AT the first candidate NOT evaluated this poll
                remember(i)
                return Command()
            cmd, _ = self.compute_consolidation([c])
            if cmd.decision != "no-op":
                budgets.consume(c.nodepool.name, self.reason)
                remember(i + 1)
                return cmd
        self._resume_key = None  # full coverage; restart at the cheapest
        return Command()


class MultiNodeConsolidation(_ConsolidationBase):
    """Largest consolidatable prefix. With the tpu solver the whole prefix
    ladder is evaluated in ONE vmapped device call
    (models/consolidation.py); the reference's binary search of full
    scheduling simulations (multinodeconsolidation.go:110-162) is the
    host fallback."""

    consolidation_type = "multi"

    def compute_command(
        self, budgets: BudgetMapping, candidates: List[Candidate]
    ) -> Command:
        candidates = self._budget_filter(
            budgets, sorted(candidates, key=lambda c: c.disruption_cost)
        )[:MULTI_NODE_CONSOLIDATION_CANDIDATE_CAP]
        if len(candidates) < 2:
            return Command()
        best = Command()
        frontier_sizes = None
        if self.ctx.provisioner.solver == "tpu":
            frontier_sizes = self._device_frontier(candidates)
        if frontier_sizes:
            passing, dubious = frontier_sizes
            # host-exact validation (price filters, spot rules) walks the
            # device-viable ladder: the largest few outright, then a binary
            # search over the REMAINING viable sizes — never the full [2,n]
            # range the reference probes (host validity is monotone in
            # prefix size, the same assumption its binary search makes)
            head, tail = passing[:4], passing[4:]
            for size in head:
                ok, cmd = self._host_validate(candidates, size)
                if ok:
                    best = cmd
                    break
            if best.decision == "no-op" and tail:
                asc = tail[::-1]  # ascending sizes
                lo, hi = 0, len(asc) - 1
                while lo <= hi:
                    mid = (lo + hi) // 2
                    ok, cmd = self._host_validate(candidates, asc[mid])
                    if ok:
                        best = cmd
                        lo = mid + 1
                    else:
                        hi = mid - 1
            if best.decision == "no-op" and dubious:
                # the device price bound said these sizes can't beat the
                # candidates' price, but the bound is only sound when the
                # device packed the fresh node like the host would — probe
                # the largest once; if the bound was wrong, search them all
                ok, cmd = self._host_validate(candidates, dubious[0])
                if ok:
                    best = cmd
                elif len(dubious) > 1:
                    asc = dubious[::-1]
                    lo, hi = 0, len(asc) - 2  # largest already probed
                    while lo <= hi:
                        mid = (lo + hi) // 2
                        ok, cmd = self._host_validate(candidates, asc[mid])
                        if ok:
                            best = cmd
                            lo = mid + 1
                        else:
                            hi = mid - 1
        if best.decision == "no-op":
            if frontier_sizes == ([], []):
                # the device proved no prefix schedulable, but its FFD is
                # conservative (sub-unit ceil/floor quantization, first-fit
                # rather than emptiest-first), so probe the easiest host prefix
                # once; under the monotonicity the binary search itself
                # assumes (larger prefixes only harder), a failed size-2
                # probe means nothing larger passes — steady-state cycles
                # pay ONE sim, not log2(n)
                ok, cmd = self._host_validate(candidates, 2)
                if ok:
                    best = cmd
                    best = self._binary_search(candidates, 3, best)
            elif frontier_sizes is None:
                # no frontier available (topology-coupled pods): reference
                # binary search; lo=2 keeps the >=2-candidate invariant
                # (multinodeconsolidation.go:111-118 never probes below a
                # 2-candidate prefix — size 1 belongs to
                # SingleNodeConsolidation)
                best = self._binary_search(candidates, 2, best)
            # a non-empty frontier whose every size failed host (price)
            # validation deliberately ends the cycle no-op: sizes outside
            # the device-viable set face the same price filters, and
            # SingleNodeConsolidation sweeps up the small wins next poll
        if best.decision != "no-op":
            for c in best.candidates:
                budgets.consume(c.nodepool.name, self.reason)
        return best

    def _binary_search(
        self, candidates: List[Candidate], lo: int, best: Command
    ) -> Command:
        """Largest host-valid prefix in [lo, len(candidates)]
        (multinodeconsolidation.go:110-162)."""
        hi = len(candidates)
        while lo <= hi:
            mid = (lo + hi) // 2
            ok, cmd = self._host_validate(candidates, mid)
            if ok:
                best = cmd
                lo = mid + 1
            else:
                hi = mid - 1
        return best

    def _host_validate(
        self, candidates: List[Candidate], size: int
    ) -> Tuple[bool, Command]:
        prefix = candidates[:size]
        cmd, _ = self.compute_consolidation(prefix)
        ok = cmd.decision == "delete"
        if cmd.decision == "replace":
            self._filter_out_same_type(cmd.replacements[0], prefix)
            ok = bool(cmd.replacements[0].instance_type_options)
        return ok, cmd

    def _device_frontier(self, candidates: List[Candidate]):
        """(passing, dubious) prefix-size lists, each largest-first, from
        the one-call device evaluation; None -> fall back to binary search.
        `passing` sizes beat the device price lower bound; `dubious` sizes
        did not, but stay reachable because the bound is only sound when
        the device packed the fresh node the way the host would."""
        from karpenter_core_tpu.models.consolidation import (
            schedulability_frontier,
        )

        frontier = schedulability_frontier(
            self.ctx.provisioner, self.ctx.cluster, candidates
        )
        if frontier is None:
            return None
        # viable prefixes: everything reschedules into at most one new node
        # AND the device price lower bound undercuts the prefix's summed
        # candidate price — a replacement at or above it would fail the
        # host's cheaper-than-candidates filter anyway, so those sizes never
        # reach a host simulation (SURVEY §7.7's device-side price filter)
        prefix_price = []
        acc = 0.0
        for c in candidates:
            acc += c.price()
            prefix_price.append(acc)
        passing, dubious = [], []
        for p, (ok, n_new, price_lb) in enumerate(frontier):
            if not ok or n_new > 1:
                continue
            if n_new == 0 or price_lb < prefix_price[p]:
                passing.append(p + 1)
            else:
                dubious.append(p + 1)
        passing.sort(reverse=True)
        dubious.sort(reverse=True)
        return passing, dubious

    @staticmethod
    def _filter_out_same_type(replacement, consolidate: List[Candidate]) -> None:
        """If the replacement's options include a type being removed, cap the
        price below the cheapest same-type candidate
        (multinodeconsolidation.go:164-217)."""
        existing = set()
        price_by_type: Dict[str, float] = {}
        for c in consolidate:
            if c.instance_type is None:
                continue
            existing.add(c.instance_type.name)
            p = c.price()
            if p > 0:
                price_by_type[c.instance_type.name] = min(
                    price_by_type.get(c.instance_type.name, math.inf), p
                )
        max_price = math.inf
        for it in replacement.instance_type_options:
            if it.name in existing and it.name in price_by_type:
                max_price = min(max_price, price_by_type[it.name])
        filter_replacement_by_price(replacement, max_price)
