"""Consolidation command validation after the 15s TTL
(reference: pkg/controllers/disruption/validation.go:56-215,
consolidation.go:46, emptiness.go:44-122).

A computed command is held for CONSOLIDATION_TTL before execution; the
cluster may change in that window (pods arriving, nominations, budget
drain). Validation then re-derives candidates and re-simulates:

* every command candidate must still pass the global candidate gates and
  the method's own predicate, with budget headroom;
* the re-simulation must reproduce the command's shape — zero fresh nodes
  for a delete, exactly one for a replace with the command's instance-type
  options a SUBSET of the fresh simulation's (the sim does no price
  filtering, so broader is fine; narrower or different means a better or
  different decision exists — recompute);
* emptiness skips the simulation and re-checks candidates are still empty.
"""
from __future__ import annotations

from typing import List, Optional

from karpenter_core_tpu.controllers.disruption.helpers import (
    build_disruption_budget_mapping,
    get_candidates,
    simulate_scheduling,
)
from karpenter_core_tpu.controllers.disruption.types import Command

CONSOLIDATION_TTL = 15.0  # consolidation.go:46


def validate_command(ctx, method, command: Command) -> Optional[str]:
    """None when still valid; otherwise the reason it is not."""
    fresh = get_candidates(
        ctx.clock,
        ctx.cluster,
        ctx.kube,
        ctx.cloud_provider,
        method.should_disrupt,
    )
    fresh_by_name = {c.name: c for c in fresh}
    validated = []
    for c in command.candidates:
        fc = fresh_by_name.get(c.name)
        if fc is None:
            return f"candidate {c.name} is no longer valid"
        validated.append(fc)

    budgets = build_disruption_budget_mapping(ctx.clock, ctx.cluster, ctx.kube)
    used: dict = {}
    for c in validated:
        pool = c.nodepool.name
        used[pool] = used.get(pool, 0) + 1
        if budgets.remaining(pool, method.reason) < used[pool]:
            return f"disruption budget exhausted for nodepool {pool!r}"

    if getattr(method, "validation", None) == "emptiness":
        # still-empty re-check only (emptiness.go:94-122)
        for c in validated:
            if c.reschedulable_pods:
                return f"candidate {c.name} is no longer empty"
        return None

    results = simulate_scheduling(ctx.provisioner, ctx.cluster, validated)
    candidate_pod_uids = {
        p.uid for c in validated for p in c.reschedulable_pods
    }
    for uid, msg in results.pod_errors.items():
        if uid in candidate_pod_uids:
            return f"candidate pods no longer schedule: {msg}"

    new_claims = [c for c in results.new_node_claims if c.pods]
    if len(new_claims) == 0:
        if not command.replacements:
            return None
        return "scheduling simulation produced new results"
    if len(new_claims) > 1 or not command.replacements:
        return "scheduling simulation produced new results"
    # replacement ITs must be a subset of the fresh simulation's options
    # (the sim does no price filtering, validation.go:195-214)
    fresh_names = {it.name for it in new_claims[0].instance_type_options}
    ours = {it.name for it in command.replacements[0].instance_type_options}
    if not ours <= fresh_names:
        return "scheduling simulation produced new results"
    return None
