"""Disruption controller: method precedence, command execution, and the
orchestration queue waiting on replacements
(reference: pkg/controllers/disruption/controller.go:54-247,
orchestration/queue.go:108-249).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodeclaim import NodeClaim
from karpenter_core_tpu.api.objects import Node
from karpenter_core_tpu.controllers.disruption.helpers import (
    build_disruption_budget_mapping,
    get_candidates,
)
from karpenter_core_tpu.controllers.disruption.methods import (
    Drift,
    Emptiness,
    MultiNodeConsolidation,
    SingleNodeConsolidation,
)
from karpenter_core_tpu.controllers.disruption.types import Command
from karpenter_core_tpu.controllers.disruption.validation import (
    CONSOLIDATION_TTL,
    validate_command,
)
from karpenter_core_tpu.kube.store import NotFoundError
from karpenter_core_tpu.scheduling.taints import DISRUPTED_NO_SCHEDULE_TAINT

COMMAND_TIMEOUT = 10 * 60.0  # orchestration/queue.go:53


@dataclass
class DisruptionContext:
    """What every method needs to see (stand-in for the Go struct embeds)."""

    kube: object
    cluster: object
    provisioner: object
    cloud_provider: object
    clock: object
    feature_gates: Dict[str, bool] = field(default_factory=dict)


@dataclass
class InFlightCommand:
    command: Command
    replacement_names: List[str]
    created_at: float


@dataclass
class PendingCommand:
    """A computed command waiting out the validation TTL
    (validation.go:83-101)."""

    command: Command
    method: object
    computed_at: float


class DisruptionController:
    def __init__(
        self,
        kube,
        cluster,
        provisioner,
        cloud_provider,
        clock,
        feature_gates: Optional[Dict[str, bool]] = None,
        recorder=None,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock
        self.recorder = recorder
        ctx = DisruptionContext(
            kube=kube,
            cluster=cluster,
            provisioner=provisioner,
            cloud_provider=cloud_provider,
            clock=clock,
            feature_gates=dict(feature_gates or {}),
        )
        self.ctx = ctx
        # method precedence (controller.go:84-93)
        self.methods = [
            Drift(ctx),
            Emptiness(ctx),
            MultiNodeConsolidation(ctx),
            SingleNodeConsolidation(ctx),
        ]
        self.in_flight: List[InFlightCommand] = []
        self.pending: List[PendingCommand] = []

    # -- the 10s poll body (controller.go:104-197) -------------------------

    def reconcile(self) -> Optional[Command]:
        self._untaint_outdated()
        self._reconcile_orchestration()
        # in-flight commands run CONCURRENTLY (orchestration/queue.go:108-141),
        # and so do pending validations: each command waits out its own 15s
        # TTL (per-command computed_at), the way every reference command gets
        # its own IsValid window (validation.go:83-101). Double-disruption is
        # prevented two ways: executed candidates by the marked_for_deletion
        # gate in new_candidate (the HasAny guard of queue.go:305), and
        # still-pending candidates by the busy-name filter below.
        executed = self._reconcile_pending()
        from karpenter_core_tpu.metrics import wiring as m

        busy = {
            c.name for p in self.pending for c in p.command.candidates
        }
        # ONE budget mapping per poll, shared by every method and
        # pre-charged with still-pending commands: concurrent pending
        # validation would otherwise let each method (and each poll) spend
        # the full budget again — marked_for_deletion only counts after
        # execution (helpers.go:197-245 counts the disrupting state; the
        # pending window is this design's addition, so it must consume too)
        budgets = build_disruption_budget_mapping(
            self.clock, self.cluster, self.kube
        )
        for p in self.pending:
            for c in p.command.candidates:
                budgets.consume(c.nodepool.name, p.method.reason)
        for method in self.methods:
            candidates = get_candidates(
                self.clock,
                self.cluster,
                self.kube,
                self.cloud_provider,
                method.should_disrupt,
            )
            candidates = [c for c in candidates if c.name not in busy]
            m.DISRUPTION_ELIGIBLE_NODES.set(
                len(candidates), {"reason": method.reason}
            )
            if not candidates:
                continue
            command = method.compute_command(budgets, candidates)
            if command.decision == "no-op":
                continue
            if getattr(method, "validation", None) is not None:
                # hold for the TTL; validated on a later pass while other
                # commands keep computing against the remaining candidates
                self.pending.append(
                    PendingCommand(
                        command=command,
                        method=method,
                        computed_at=self.clock.now(),
                    )
                )
                busy.update(c.name for c in command.candidates)
                continue
            self._execute(command)
            return command
        if executed:
            return executed[-1]
        if not self.pending:
            self.cluster.mark_consolidated()
        return None

    def validation_wait_remaining(self) -> float:
        """Seconds until the NEXT pending command's TTL elapses (0 if none)."""
        if not self.pending:
            return 0.0
        return min(
            max(CONSOLIDATION_TTL - self.clock.since(p.computed_at), 0.0)
            for p in self.pending
        )

    def _reconcile_pending(self) -> List[Command]:
        """Validate + execute every pending command whose TTL has elapsed."""
        from karpenter_core_tpu.metrics import wiring as m

        executed: List[Command] = []
        still_waiting: List[PendingCommand] = []
        for pending in self.pending:
            if self.clock.since(pending.computed_at) < CONSOLIDATION_TTL:
                still_waiting.append(pending)
                continue
            err = validate_command(self.ctx, pending.method, pending.command)
            if err is not None:
                # invalidated: drop; the next poll recomputes from fresh state
                m.DISRUPTION_VALIDATION_FAILURES.inc(
                    {"reason": pending.method.reason}
                )
                if self.recorder is not None:
                    from karpenter_core_tpu.events import Event

                    self.recorder.publish(Event(
                        involved_object="Deployment/karpenter",
                        type="Normal",
                        reason="DisruptionValidationFailed",
                        message=err,
                    ))
                continue
            self._execute(pending.command)
            executed.append(pending.command)
        self.pending = still_waiting
        return executed

    def _untaint_outdated(self) -> None:
        """Crash recovery (controller.go:127-141): nodes carrying the
        disruption taint that belong to no active command — a restarted
        operator has an empty in-flight list while the store still shows
        taints from interrupted commands — get untainted so they rejoin
        scheduling instead of staying cordoned forever."""
        active = {
            c.name
            for cmd in self.in_flight
            for c in cmd.command.candidates
        } | {c.name for p in self.pending for c in p.command.candidates}
        for node in self.kube.list_nodes():
            if node.name in active:
                continue
            if node.metadata.deletion_timestamp is not None:
                continue  # termination owns the taint during teardown
            kept = [
                t for t in node.taints
                if t.key != DISRUPTED_NO_SCHEDULE_TAINT.key
            ]
            if len(kept) != len(node.taints):
                node.taints = kept
                self.kube.update(node)

    # -- execution (controller.go:203-247) ---------------------------------

    def _execute(self, command: Command) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        m.DISRUPTION_DECISIONS.inc(
            {"decision": command.decision, "reason": command.reason}
        )
        if self.recorder is not None:
            from karpenter_core_tpu.events import Event

            self.recorder.publish(*[
                Event(
                    involved_object=f"Node/{c.name}",
                    type="Normal",
                    reason="DisruptionTerminating",
                    message=(
                        f"Disrupting node via {command.reason} "
                        f"({command.decision})"
                    ),
                )
                for c in command.candidates
            ])
        # taint + mark so the provisioner stops using the candidates
        for c in command.candidates:
            node = self.kube.get(Node, c.name)
            if node is None:
                continue
            if not any(
                t.key == DISRUPTED_NO_SCHEDULE_TAINT.key for t in node.taints
            ):
                node.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
                self.kube.update(node)
            c.state_node.marked_for_deletion = True

        replacement_names = []
        for claim in command.replacements:
            nc = claim.template.to_node_claim(
                claim.requirements, claim.instance_type_options, claim.requests
            )
            nc.metadata.finalizers.append(apilabels.TERMINATION_FINALIZER)
            self.kube.create(nc)
            replacement_names.append(nc.name)

        self.in_flight.append(
            InFlightCommand(
                command=command,
                replacement_names=replacement_names,
                created_at=self.clock.now(),
            )
        )

    # -- orchestration (orchestration/queue.go:163-249) --------------------

    def _reconcile_orchestration(self) -> None:
        remaining = []
        for cmd in self.in_flight:
            if self._finished(cmd):
                continue
            if self.clock.since(cmd.created_at) > COMMAND_TIMEOUT:
                self._rollback(cmd)
                continue
            remaining.append(cmd)
        self.in_flight = remaining

    def _finished(self, cmd: InFlightCommand) -> bool:
        # all replacements must be initialized before candidates die
        # (waitOrTerminate, orchestration/queue.go:221-249)
        for name in cmd.replacement_names:
            claim = self.kube.get(NodeClaim, name)
            if claim is None:
                # replacement failed (e.g. insufficient capacity): abort the
                # whole command and roll back (queue.go:181-209)
                self._rollback(cmd)
                return True
            if not claim.is_initialized():
                return False
        for c in cmd.command.candidates:
            node = self.kube.get(Node, c.name)
            if node is not None and node.metadata.deletion_timestamp is None:
                try:
                    self.kube.delete(node)
                except NotFoundError:
                    pass
        # command completes when every candidate node is gone
        return all(
            self.kube.get(Node, c.name) is None for c in cmd.command.candidates
        )

    def _rollback(self, cmd: InFlightCommand) -> None:
        for c in cmd.command.candidates:
            node = self.kube.get(Node, c.name)
            if node is not None and node.metadata.deletion_timestamp is None:
                node.taints = [
                    t
                    for t in node.taints
                    if t.key != DISRUPTED_NO_SCHEDULE_TAINT.key
                ]
                self.kube.update(node)
            c.state_node.marked_for_deletion = False
