"""Disruption helpers: the scheduling-simulation bridge into L4, candidate
collection, and budget math (reference: pkg/controllers/disruption/
helpers.go:49-245)."""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodepool import REASON_ALL
from karpenter_core_tpu.controllers.disruption.types import (
    Candidate,
    CandidateError,
    new_candidate,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
    Results,
)


def simulate_scheduling(
    provisioner,
    cluster,
    candidates: List[Candidate],
) -> Results:
    """Re-enter the full provisioning scheduler with the candidates' nodes
    removed and their pods queued (helpers.go:49-113). The scheduler
    assembly (solver strategy, volume state, topology exclusions) is the
    provisioner's own, so the simulation cannot drift from the real solve."""
    pods = provisioner.pending_pods() + provisioner.deleting_node_pods()
    for c in candidates:
        pods.extend(c.reschedulable_pods)
    pods, volume_errors = provisioner._prepare_volumes(pods)
    scheduler = provisioner.new_scheduler(
        pods, excluded_nodes={c.name for c in candidates}
    )
    results = scheduler.solve(pods)
    results.pod_errors.update(volume_errors)
    return results


def get_candidates(
    clock,
    cluster,
    kube,
    cloud_provider,
    should_disrupt: Callable[[Candidate], bool],
) -> List[Candidate]:
    """(helpers.go:144-161)"""
    from karpenter_core_tpu.utils.pdb import Limits

    nodepools = {np.name: np for np in kube.list_nodepools()}
    instance_types = {
        name: cloud_provider.get_instance_types(np)
        for name, np in nodepools.items()
    }
    pdb_limits = Limits.from_kube(kube)
    out = []
    for sn in cluster.nodes():
        try:
            c = new_candidate(
                clock, cluster, sn, nodepools, instance_types,
                pdb_limits=pdb_limits,
            )
        except CandidateError:
            continue
        if should_disrupt(c):
            out.append(c)
    return out


class BudgetMapping:
    """Allowed disruptions per (nodepool, reason) minus nodes already
    disrupting (helpers.go:197-245)."""

    def __init__(self, allowed: Dict[str, Dict[str, int]]):
        self.allowed = allowed

    def remaining(self, nodepool_name: str, reason: str) -> int:
        pool = self.allowed.get(nodepool_name, {})
        if reason in pool:
            return pool[reason]
        return pool.get(REASON_ALL, 1 << 30)

    def consume(self, nodepool_name: str, reason: str, n: int = 1) -> None:
        pool = self.allowed.setdefault(nodepool_name, {})
        for r in (reason, REASON_ALL):
            if r in pool:
                pool[r] = max(pool[r] - n, 0)


def build_disruption_budget_mapping(clock, cluster, kube) -> BudgetMapping:
    allowed: Dict[str, Dict[str, int]] = {}
    now = clock.now()
    for np in kube.list_nodepools():
        totals = 0
        disrupting = 0
        for sn in cluster.nodes():
            if sn.nodepool_name != np.name:
                continue
            if not sn.initialized():
                continue
            totals += 1
            # draining nodes consume budget until they're gone
            # (helpers.go:197-245 counts MarkedForDeletion)
            if sn.marked_for_deletion or sn.deleting():
                disrupting += 1
        per_reason: Dict[str, int] = {}
        for budget in np.spec.disruption.budgets:
            budget_reasons = budget.reasons or [REASON_ALL]
            cap = budget.allowed_disruptions(totals, now)
            for r in budget_reasons:
                per_reason[r] = min(per_reason.get(r, 1 << 30), cap)
        for r in list(per_reason):
            per_reason[r] = max(per_reason[r] - disrupting, 0)
        allowed[np.name] = per_reason
    return BudgetMapping(allowed)
