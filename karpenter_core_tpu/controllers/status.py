"""Condition-transition observability — the operatorpkg status controllers.

The reference registers a status controller per CRD kind
(pkg/controllers/controllers.go:103-105: status.NewController[*v1.NodeClaim],
[*v1.NodePool], and the generic Node variant); they are the fleet's primary
condition-debugging surface, emitting a metric + event on every condition
flip. The rebuild is one observer that diffs each object's ConditionSet
against its last-seen snapshot per reconcile pass — the synchronous
equivalent of the reference's watch-driven reconciler.
"""
from __future__ import annotations

from typing import Dict, Tuple

from karpenter_core_tpu.events.recorder import Event
from karpenter_core_tpu.metrics import wiring as m


class StatusController:
    def __init__(self, kube, recorder, clock):
        self.kube = kube
        self.recorder = recorder
        self.clock = clock
        # (kind, object name, condition type) -> (status, reason)
        self._seen: Dict[Tuple[str, str, str], Tuple[str, str]] = {}

    def reconcile(self) -> None:
        live = set()
        for kind, objs in (
            ("NodeClaim", self.kube.list_nodeclaims()),
            ("NodePool", self.kube.list_nodepools()),
        ):
            for obj in objs:
                for cond in obj.conditions.all():
                    key = (kind, obj.name, cond.type)
                    live.add(key)
                    prev = self._seen.get(key)
                    cur = (cond.status, cond.reason)
                    if prev == cur:
                        continue
                    self._seen[key] = cur
                    m.STATUS_CONDITION_TRANSITIONS.inc(
                        {
                            "kind": kind,
                            "type": cond.type,
                            "status": cond.status,
                        }
                    )
                    self.recorder.publish(
                        Event(
                            involved_object=f"{kind}/{obj.name}",
                            type="Normal",
                            reason=f"{cond.type}{cond.status}",
                            message=(
                                f"condition {cond.type} -> {cond.status}"
                                + (f" ({cond.reason})" if cond.reason else "")
                            ),
                        )
                    )
        # deleted objects stop contributing series (the reference's gauge
        # stores delete by object on DeletedFinalStateUnknown)
        for key in list(self._seen):
            if key not in live:
                del self._seen[key]
        m.STATUS_CONDITION_COUNT.reset()
        for (kind, _name, ctype), (status, _reason) in self._seen.items():
            labels = {"kind": kind, "type": ctype, "status": status}
            m.STATUS_CONDITION_COUNT.set(
                m.STATUS_CONDITION_COUNT.value(labels) + 1, labels
            )
