"""NodeClaim periphery: expiration, garbage collection, consistency
(reference: pkg/controllers/nodeclaim/{expiration,garbagecollection,
consistency}/controller.go).
"""
from __future__ import annotations

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodeclaim import (
    COND_CONSISTENT_STATE_FOUND,
    NodeClaim,
)
from karpenter_core_tpu.api.objects import Node
from karpenter_core_tpu.cloudprovider.types import NodeClaimNotFoundError
from karpenter_core_tpu.events import Event
from karpenter_core_tpu.utils import resources as resutil


class Expiration:
    """Forceful deletion of claims past expireAfter
    (expiration/controller.go:54-70)."""

    def __init__(self, kube, clock):
        self.kube = kube
        self.clock = clock

    def reconcile(self, claim: NodeClaim) -> None:
        if claim.metadata.deletion_timestamp is not None:
            return
        expire = claim.spec.expire_after.seconds
        if expire is None:
            return
        if self.clock.since(claim.metadata.creation_timestamp) >= expire:
            self.kube.delete(claim)


class GarbageCollection:
    """Reconcile cloud<->cluster drift in both directions: claims whose
    instance vanished are deleted; instances without a claim are terminated
    (garbagecollection/controller.go:59-116, 2-minute sweep)."""

    SWEEP_INTERVAL = 120.0

    def __init__(self, kube, cloud_provider, clock):
        self.kube = kube
        self.cloud_provider = cloud_provider
        self.clock = clock
        self._last_sweep: float = float("-inf")

    def reconcile(self) -> None:
        # interval-gated sweep, like the reference's 2-minute singleton
        if self.clock.now() - self._last_sweep < self.SWEEP_INTERVAL:
            return
        self._last_sweep = self.clock.now()
        claims = self.kube.list_nodeclaims()
        claimed_ids = {
            c.status.provider_id for c in claims if c.status.provider_id
        }
        cloud_claims = self.cloud_provider.list()
        live_ids = {
            cc.status.provider_id for cc in cloud_claims if cc.status.provider_id
        }
        # direction 1: claims pointing at vanished instances
        for claim in claims:
            if not claim.is_launched() or not claim.status.provider_id:
                continue
            if claim.metadata.deletion_timestamp is not None:
                continue
            if claim.status.provider_id not in live_ids:
                self.kube.delete(claim)
        # direction 2: cloud instances with no claim (leaked)
        for cloud_claim in cloud_claims:
            pid = cloud_claim.status.provider_id
            if pid and pid not in claimed_ids:
                try:
                    self.cloud_provider.delete(cloud_claim)
                except NodeClaimNotFoundError:
                    pass


class Consistency:
    """Scan for node<->claim invariant violations, e.g. a node whose
    registered capacity shrank below the claim's promise
    (consistency/controller.go:62-146, 10-minute scan)."""

    def __init__(self, kube, recorder, clock):
        self.kube = kube
        self.recorder = recorder
        self.clock = clock

    def reconcile(self, claim: NodeClaim) -> None:
        if not claim.is_registered() or not claim.status.node_name:
            return
        node = self.kube.get(Node, claim.status.node_name)
        if node is None:
            return
        failures = []
        # the node must expose at least the resources the claim promised
        for name, qty in claim.status.capacity.items():
            have = node.status.capacity.get(name, 0.0)
            if have < qty * (1.0 - 1e-9):
                failures.append(
                    f"expected {qty:g} of resource {name}, but found {have:g} "
                    f"({have / qty * 100.0:.1f}% of expected)"
                )
        if failures:
            for msg in failures:
                self.recorder.publish(
                    Event(
                        involved_object=f"NodeClaim/{claim.name}",
                        type="Warning",
                        reason="FailedConsistencyCheck",
                        message=msg,
                    )
                )
            claim.conditions.set_false(
                COND_CONSISTENT_STATE_FOUND,
                "ConsistencyCheckFailed",
                "; ".join(failures),
                now=self.clock.now(),
            )
        else:
            claim.conditions.set_true(
                COND_CONSISTENT_STATE_FOUND, "ConsistentStateFound",
                now=self.clock.now(),
            )
