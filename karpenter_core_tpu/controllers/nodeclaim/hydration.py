"""Hydration: backfill labels newer versions expect onto pre-existing
objects (reference: pkg/controllers/nodeclaim/hydration/controller.go:41-78,
pkg/controllers/node/hydration/controller.go:40-75).

The nodeclass label key is derived from the claim's nodeClassRef group/kind
(v1.NodeClassLabelKey); both the NodeClaim and its Node get it stamped.
"""
from __future__ import annotations

from karpenter_core_tpu.api.nodeclaim import NodeClaim


def node_class_label_key(group: str, kind: str) -> str:
    return f"{group}/{kind.lower()}" if group else kind.lower()


class Hydration:
    def __init__(self, kube):
        self.kube = kube

    def reconcile(self, claim: NodeClaim) -> None:
        ref = claim.spec.node_class_ref
        if ref is None or not ref.name:
            return
        key = node_class_label_key(ref.group, ref.kind)
        if claim.metadata.labels.get(key) != ref.name:
            claim.metadata.labels[key] = ref.name
            self.kube.update(claim)
        node = (
            self.kube.get_node_by_provider_id(claim.status.provider_id)
            if claim.status.provider_id
            else None
        )
        if node is not None and node.metadata.labels.get(key) != ref.name:
            node.metadata.labels[key] = ref.name
            self.kube.update(node)
