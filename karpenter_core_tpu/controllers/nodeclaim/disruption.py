"""NodeClaim disruption conditions: Consolidatable and Drifted, plus the
pod-event timestamping that drives consolidateAfter
(reference: pkg/controllers/nodeclaim/disruption/{consolidation,drift}.go,
podevents/controller.go:41-99).
"""
from __future__ import annotations

from typing import Optional

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodeclaim import (
    COND_CONSOLIDATABLE,
    COND_DRIFTED,
    COND_INITIALIZED,
    NodeClaim,
)
from karpenter_core_tpu.api.nodepool import NodePool
from karpenter_core_tpu.scheduling import Requirements

POD_EVENT_DEDUPE = 5.0  # podevents/controller.go 5s dedupe
DRIFT_REASON_NODEPOOL_STATIC = "NodePoolDrifted"
DRIFT_REASON_REQUIREMENTS = "RequirementsDrifted"
DRIFT_REASON_IT_GONE = "InstanceTypeNotFound"


class NodeClaimDisruption:
    def __init__(self, kube, cloud_provider, clock):
        self.kube = kube
        self.cloud_provider = cloud_provider
        self.clock = clock

    def reconcile(self, claim: NodeClaim) -> None:
        if claim.metadata.deletion_timestamp is not None:
            return
        pool = self.kube.get(NodePool, claim.nodepool_name)
        if pool is None:
            return
        self._reconcile_consolidatable(pool, claim)
        self._reconcile_drifted(pool, claim)

    # -- Consolidatable (nodeclaim/disruption/consolidation.go:40-78) ------

    def _reconcile_consolidatable(self, pool: NodePool, claim: NodeClaim) -> None:
        consolidate_after = pool.spec.disruption.consolidate_after.seconds
        if consolidate_after is None:  # Never
            claim.conditions.clear(COND_CONSOLIDATABLE)
            return
        init = claim.conditions.get(COND_INITIALIZED)
        if init is None or not claim.is_initialized():
            claim.conditions.clear(COND_CONSOLIDATABLE)
            return
        t = claim.status.last_pod_event_time or init.last_transition_time
        if self.clock.since(t) < consolidate_after:
            claim.conditions.clear(COND_CONSOLIDATABLE)
            return
        claim.conditions.set_true(COND_CONSOLIDATABLE, "Consolidatable", now=self.clock.now())

    # -- Drifted (nodeclaim/disruption/drift.go:55-120) --------------------

    def _reconcile_drifted(self, pool: NodePool, claim: NodeClaim) -> None:
        if not claim.is_launched():
            return
        reason = self._drift_reason(pool, claim)
        if reason:
            claim.conditions.set_true(COND_DRIFTED, reason, now=self.clock.now())
        else:
            claim.conditions.clear(COND_DRIFTED)

    def _drift_reason(self, pool: NodePool, claim: NodeClaim) -> Optional[str]:
        # static hash drift (drift.go areStaticFieldsDrifted): annotation vs
        # annotation, gated — missing hash on either side or a hash-VERSION
        # mismatch is NOT drift (the hash controller migrates versions by
        # re-stamping claims, hash/controller.go:70-124)
        pool_hash = pool.metadata.annotations.get(
            apilabels.NODEPOOL_HASH_ANNOTATION_KEY
        )
        claim_hash = claim.metadata.annotations.get(
            apilabels.NODEPOOL_HASH_ANNOTATION_KEY
        )
        pool_ver = pool.metadata.annotations.get(
            apilabels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY
        )
        claim_ver = claim.metadata.annotations.get(
            apilabels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY
        )
        if (
            pool_hash is not None
            and claim_hash is not None
            and pool_ver == claim_ver
            and claim_hash != pool_hash
        ):
            return DRIFT_REASON_NODEPOOL_STATIC
        # requirements drift: the claim's committed labels must still satisfy
        # the pool's requirements (drift.go:144-154 uses Compatible, whose
        # undefined-key rule also drifts claims when the pool adds a
        # requirement on a key the claim's labels never defined)
        pool_reqs = Requirements.from_node_selector_requirements_with_min_values(
            pool.spec.template.requirements
        )
        claim_labels = Requirements.from_labels(claim.metadata.labels)
        if claim_labels.compatible(pool_reqs):
            return DRIFT_REASON_REQUIREMENTS
        # stale instance type: vanished from the catalog, or none of its
        # remaining offerings is available+compatible with the claim's
        # committed zone/capacity-type (drift.go instanceTypeNotFound family)
        it_name = claim.metadata.labels.get(apilabels.LABEL_INSTANCE_TYPE)
        if it_name is not None:
            it = next(
                (
                    i
                    for i in self.cloud_provider.get_instance_types(pool)
                    if i.name == it_name
                ),
                None,
            )
            if it is None:
                return DRIFT_REASON_IT_GONE
            if not it.offerings.available().has_compatible(claim_labels):
                return DRIFT_REASON_IT_GONE
        return self.cloud_provider.is_drifted(claim) or None


class PodEvents:
    """Stamps NodeClaim.status.last_pod_event_time on pod churn
    (podevents/controller.go:41-99)."""

    def __init__(self, kube, cluster, clock):
        self.kube = kube
        self.cluster = cluster
        self.clock = clock
        kube.watch(self._on_event)

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind != "Pod":
            return
        node_name = getattr(obj, "node_name", "")
        if not node_name:
            return
        for claim in self.kube.list_nodeclaims():
            if claim.status.node_name == node_name:
                now = self.clock.now()
                last = claim.status.last_pod_event_time
                if last is None or now - last >= POD_EVENT_DEDUPE:
                    claim.status.last_pod_event_time = now
                break
