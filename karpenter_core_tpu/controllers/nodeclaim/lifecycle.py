"""NodeClaim lifecycle: Launch → Registration → Initialization, plus
liveness TTL and finalizer-driven teardown
(reference: pkg/controllers/nodeclaim/lifecycle/{controller,launch,
registration,initialization,liveness}.go).
"""
from __future__ import annotations

from typing import Optional

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodeclaim import (
    COND_INITIALIZED,
    COND_INSTANCE_TERMINATING,
    COND_LAUNCHED,
    COND_REGISTERED,
    NodeClaim,
)
from karpenter_core_tpu.api.objects import Node
from karpenter_core_tpu.cloudprovider.types import (
    CloudProviderError,
    CreateError,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    NodeClassNotReadyError,
)
from karpenter_core_tpu.scheduling import Requirements
from karpenter_core_tpu.scheduling.taints import UNREGISTERED_NO_EXECUTE_TAINT

REGISTRATION_TTL = 15 * 60.0  # liveness.go:41


class NodeClaimLifecycle:
    def __init__(
        self,
        kube,
        cluster,
        cloud_provider,
        clock,
        unavailable_offerings=None,
        recorder=None,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock
        # ICE cache the launch path populates from typed error context; the
        # provisioner's solve paths consume it (cloudprovider/
        # unavailableofferings.py) — None keeps the pre-cache behavior
        self.unavailable_offerings = unavailable_offerings
        self.recorder = recorder

    def reconcile(self, claim: NodeClaim) -> None:
        if claim.metadata.deletion_timestamp is not None:
            self._finalize(claim)
            return
        if apilabels.TERMINATION_FINALIZER not in claim.metadata.finalizers:
            claim.metadata.finalizers.append(apilabels.TERMINATION_FINALIZER)
            self.kube.update(claim)
        # liveness backstop (liveness.go:41): a claim not Registered within
        # the TTL is reaped REGARDLESS of launch state — a permanently
        # failing launch (CreateError each pass) must not retry forever
        if not claim.is_registered() and self.clock.since(
            claim.metadata.creation_timestamp
        ) > REGISTRATION_TTL:
            self.kube.delete(claim)
            return
        if not claim.is_launched():
            self._launch(claim)
        if claim.is_launched() and not claim.is_registered():
            self._register(claim)
        if claim.is_registered() and not claim.is_initialized():
            self._initialize(claim)

    # -- launch (launch.go:45) --------------------------------------------

    def _launch(self, claim: NodeClaim) -> None:
        user_labels = dict(claim.metadata.labels)
        try:
            self.cloud_provider.create(claim)
        except InsufficientCapacityError as e:
            # terminal for this claim: mark the stocked-out offerings in the
            # ICE cache so the re-solve excludes them (both solve paths AND
            # the provider's own pick consume the cache), then delete so the
            # provisioner retries onto the next-cheapest AVAILABLE offering
            # (launch.go terminal-error path + the AWS ICE cache)
            self._record_insufficient_capacity(claim, e)
            self.kube.delete(claim)
            return
        except NodeClassNotReadyError:
            # terminal against a (possibly fixed) class; retried via re-solve
            self.kube.delete(claim)
            return
        except CreateError as e:
            # non-terminal: surface the provider's typed condition so the
            # failure is visible while retries continue (launch.go sets
            # Launched=False from the CreateError's reason/message)
            claim.conditions.set_false(
                COND_LAUNCHED,
                e.condition_reason or "LaunchFailed",
                message=e.condition_message or str(e),
                now=self.clock.now(),
            )
            self.kube.update(claim)
            return
        except CloudProviderError:
            return  # retried next reconcile
        # PopulateNodeClaimDetails (launch.go:122-133): provider-resolved
        # labels < single-value requirement labels < user-defined labels
        req_labels = Requirements.from_node_selector_requirements_with_min_values(
            claim.spec.requirements
        ).to_labels()
        claim.metadata.labels = {
            **claim.metadata.labels,
            **req_labels,
            **user_labels,
        }
        self.kube.update(claim)

    def _record_insufficient_capacity(
        self, claim: NodeClaim, err: InsufficientCapacityError
    ) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        keys = getattr(err, "offerings", ()) or ()
        if self.unavailable_offerings is not None:
            for key in keys:
                self.unavailable_offerings.mark(key)
        if keys:
            for key in keys:
                m.INSUFFICIENT_CAPACITY_ERRORS.inc({
                    "capacity_type": key.capacity_type, "zone": key.zone,
                })
        else:
            m.INSUFFICIENT_CAPACITY_ERRORS.inc(
                {"capacity_type": "", "zone": ""}
            )
        if self.recorder is not None:
            from karpenter_core_tpu.events import Event

            self.recorder.publish(Event(
                involved_object=f"NodeClaim/{claim.name}",
                type="Warning",
                reason="InsufficientCapacity",
                message=str(err),
            ))

    # -- registration (registration.go:43) --------------------------------

    def _register(self, claim: NodeClaim) -> None:
        node = self.kube.get_node_by_provider_id(claim.status.provider_id)
        if node is None:
            return  # liveness reap lives in reconcile()'s TTL backstop
        node.taints = [
            t
            for t in node.taints
            if not (
                t.key == UNREGISTERED_NO_EXECUTE_TAINT.key
                and t.effect == UNREGISTERED_NO_EXECUTE_TAINT.effect
            )
        ]
        for taint in list(claim.spec.taints) + list(claim.spec.startup_taints):
            if not any(
                t.key == taint.key and t.effect == taint.effect
                for t in node.taints
            ):
                node.taints.append(taint)
        node.metadata.labels.update(claim.metadata.labels)
        node.metadata.labels[apilabels.NODE_REGISTERED_LABEL_KEY] = "true"
        if apilabels.TERMINATION_FINALIZER not in node.metadata.finalizers:
            node.metadata.finalizers.append(apilabels.TERMINATION_FINALIZER)
        self.kube.update(node)
        claim.status.node_name = node.name
        claim.conditions.set_true(COND_REGISTERED, "Registered", now=self.clock.now())
        self.kube.update(claim)

    # -- initialization (initialization.go:47) -----------------------------

    def _initialize(self, claim: NodeClaim) -> None:
        node = self.kube.get(Node, claim.status.node_name)
        if node is None or not node.ready():
            return
        # startup taints must clear and registered resources must be present
        startup = list(claim.spec.startup_taints)
        if any(
            any(t.key == s.key and t.effect == s.effect for s in startup)
            for t in node.taints
        ):
            return
        if not node.status.allocatable:
            return
        node.metadata.labels[apilabels.NODE_INITIALIZED_LABEL_KEY] = "true"
        self.kube.update(node)
        claim.conditions.set_true(COND_INITIALIZED, "Initialized", now=self.clock.now())
        self.kube.update(claim)

    # -- teardown (lifecycle/controller.go:111-285) ------------------------

    def _finalize(self, claim: NodeClaim) -> None:
        if apilabels.TERMINATION_FINALIZER not in claim.metadata.finalizers:
            return
        # no instance to delete when none was ever created — keyed on
        # provider_id, NOT the Launched condition: a provider can create
        # the instance and record its id, then fail before the condition
        # lands (lifecycle/controller.go keys the skip on an empty
        # ProviderID; gc.py's leak sweep uses the same signal)
        if claim.status.provider_id:
            try:
                self.cloud_provider.delete(claim)
            except NodeClaimNotFoundError:
                pass  # instance already gone
        claim.conditions.set_true(COND_INSTANCE_TERMINATING, "Terminating", now=self.clock.now())
        claim.metadata.finalizers.remove(apilabels.TERMINATION_FINALIZER)
        self.kube.update(claim)
