"""NodePool periphery: counter, hash, readiness, validation
(reference: pkg/controllers/nodepool/{counter,hash,readiness,validation}/
controller.go).
"""
from __future__ import annotations

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.labels import HASH_VERSION
from karpenter_core_tpu.api.nodepool import (
    COND_NODEPOOL_NODECLASS_READY,
    COND_NODEPOOL_VALIDATION_SUCCEEDED,
    NodePool,
)
from karpenter_core_tpu.utils import resources as resutil



class Counter:
    """Aggregate in-use resources into NodePool.status.resources — feeds the
    Limits check (counter/controller.go:42-114)."""

    def __init__(self, kube, cluster):
        self.kube = kube
        self.cluster = cluster

    def reconcile(self, pool: NodePool) -> None:
        usage: dict = {"nodes": 0.0}
        for sn in self.cluster.nodes():
            if sn.nodepool_name != pool.name or sn.deleting():
                continue
            usage = resutil.merge(usage, sn.capacity())
            usage["nodes"] += 1.0
        pool.status.resources = usage


class Hash:
    """Maintain the drift hash annotation incl. hash-version migration
    (hash/controller.go:39-124)."""

    def __init__(self, kube):
        self.kube = kube

    def reconcile(self, pool: NodePool) -> None:
        current = pool.static_hash()
        ann = pool.metadata.annotations
        stale_version = (
            ann.get(apilabels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY) != HASH_VERSION
        )
        if ann.get(apilabels.NODEPOOL_HASH_ANNOTATION_KEY) == current and not stale_version:
            return
        if stale_version:
            # hash-version migration: re-stamp claims so a mechanical hash
            # change isn't read as drift. Claims already marked Drifted
            # keep their STALE HASH — the condition reflects a real config
            # difference a re-stamp would erase — but still get the new
            # hash VERSION, or the version gate would mask that real drift
            # from then on (hash/controller.go:102-113 updates the version
            # annotation on drifted claims and skips only the hash)
            for claim in self.kube.list_nodeclaims():
                if claim.nodepool_name != pool.name:
                    continue
                if not claim.conditions.is_true("Drifted"):
                    claim.metadata.annotations[
                        apilabels.NODEPOOL_HASH_ANNOTATION_KEY
                    ] = current
                claim.metadata.annotations[
                    apilabels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY
                ] = HASH_VERSION
        ann[apilabels.NODEPOOL_HASH_ANNOTATION_KEY] = current
        ann[apilabels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = HASH_VERSION
        self.kube.update(pool)


class Readiness:
    """NodePool Ready from NodeClass readiness (readiness/controller.go:40-104).
    The kwok/fake providers have no NodeClass objects, so absence of a
    node_class_ref reads as ready."""

    def __init__(self, kube, cloud_provider, clock):
        self.kube = kube
        self.cloud_provider = cloud_provider
        self.clock = clock

    def reconcile(self, pool: NodePool) -> None:
        ref = pool.spec.template.node_class_ref
        supported = getattr(
            self.cloud_provider, "supported_node_classes", lambda: None
        )()
        if ref is None or supported is None:
            pool.conditions.set_true(
                COND_NODEPOOL_NODECLASS_READY, "NodeClassReady",
                now=self.clock.now(),
            )
            return
        if ref.kind in supported:
            pool.conditions.set_true(
                COND_NODEPOOL_NODECLASS_READY, "NodeClassReady",
                now=self.clock.now(),
            )
        else:
            pool.conditions.set_false(
                COND_NODEPOOL_NODECLASS_READY,
                "NodeClassNotSupported",
                f"node class {ref.kind!r} not supported by provider",
                now=self.clock.now(),
            )


class Validation:
    """Runtime validation -> Ready=false (validation/controller.go:37-77)."""

    def __init__(self, kube, clock):
        self.kube = kube
        self.clock = clock

    def reconcile(self, pool: NodePool) -> None:
        errs = []
        for taint in pool.spec.template.taints:
            if not taint.key:
                errs.append("taint with empty key")
        for r in pool.spec.template.requirements:
            if r.operator in ("In", "NotIn") and not r.values:
                errs.append(f"requirement {r.key} has operator {r.operator} with no values")
            if r.operator in ("Gt", "Lt"):
                try:
                    int(r.values[0])
                except (IndexError, ValueError):
                    errs.append(f"requirement {r.key} {r.operator} needs one integer value")
            if apilabels.is_restricted_label(r.key):
                errs.append(f"requirement on restricted label {r.key}")
        for key in pool.spec.template.labels:
            if apilabels.is_restricted_label(key):
                errs.append(f"restricted label {key}")
        for budget in pool.spec.disruption.budgets:
            if budget.schedule is not None and budget.duration is None:
                errs.append("budget schedule requires a duration")
        if errs:
            pool.conditions.set_false(
                COND_NODEPOOL_VALIDATION_SUCCEEDED,
                "ValidationFailed",
                "; ".join(errs),
                now=self.clock.now(),
            )
        else:
            pool.conditions.set_true(
                COND_NODEPOOL_VALIDATION_SUCCEEDED, "ValidationSucceeded",
                now=self.clock.now(),
            )

    def is_ready(self, pool: NodePool) -> bool:
        return not pool.conditions.is_false(COND_NODEPOOL_VALIDATION_SUCCEEDED)
