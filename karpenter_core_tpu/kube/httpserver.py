"""HTTP apiserver over a KubeStore — the second side of the client seam.

An HTTP-faithful stand-in for a real kube-apiserver (envtest's role, run
as a SEPARATE PROCESS): the store's apiserver contracts — resource-version
conflicts (409), finalizer-gated deletes, NotFound (404), PDB-gated
eviction (429), bind subresource — surface as their HTTP status codes, and
watches surface as a resource-version-cursored event feed the way the real
watch API replays from a resourceVersion. kube/httpclient.py speaks this
protocol and passes the same conformance battery as the in-memory store
(tests/test_client_conformance.py), which is what makes the KubeClient
protocol (kube/client.py) a proven seam rather than a declared one.
Reference anchors: operator.go:105-206 (client construction),
pkg/test/environment.go:60-80 (envtest as the test apiserver).

Run: ``python -m karpenter_core_tpu.kube.httpserver --port 8123``
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Tuple
from urllib.parse import parse_qs, urlparse

from karpenter_core_tpu.kube import serial
from karpenter_core_tpu.kube.store import (
    ConflictError,
    KubeStore,
    NotFoundError,
    TooManyRequestsError,
)

_LIST_KINDS = {
    "pods": "list_pods",
    "nodes": "list_nodes",
    "nodeclaims": "list_nodeclaims",
    "nodepools": "list_nodepools",
    "daemonsets": "list_daemonsets",
    "volumeattachments": "list_volume_attachments",
    "poddisruptionbudgets": "list_pdbs",
}

# -- shared handler plumbing (also used by solver/service.py, the solverd
# sidecar — one definition of "send a body with correct framing") ----------


def send_body(
    handler: BaseHTTPRequestHandler,
    code: int,
    body: bytes,
    ctype: str = "application/json",
    headers: dict = None,
) -> None:
    handler.send_response(code)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(body)))
    for k, v in (headers or {}).items():
        handler.send_header(k, v)
    handler.end_headers()
    handler.wfile.write(body)


def read_body(handler: BaseHTTPRequestHandler) -> bytes:
    n = int(handler.headers.get("Content-Length", "0"))
    return handler.rfile.read(n) if n else b""


# kinds the GET-by-name path serves (plural -> API class)
_GET_KINDS = {}


def _get_kinds():
    if not _GET_KINDS:
        from karpenter_core_tpu.api import objects as o
        from karpenter_core_tpu.api.nodeclaim import NodeClaim
        from karpenter_core_tpu.api.nodepool import NodePool

        _GET_KINDS.update({
            "pods": o.Pod,
            "nodes": o.Node,
            "nodeclaims": NodeClaim,
            "nodepools": NodePool,
            "daemonsets": o.DaemonSet,
            "volumeattachments": o.VolumeAttachment,
            "poddisruptionbudgets": o.PodDisruptionBudget,
            "persistentvolumeclaims": o.PersistentVolumeClaim,
            "persistentvolumes": o.PersistentVolume,
            "storageclasses": o.StorageClass,
            "csinodes": o.CSINode,
        })
    return _GET_KINDS


class ApiServer:
    """The store plus an event journal for resource-version watches."""

    def __init__(self, store: KubeStore):
        self.store = store
        self.events: List[Tuple[int, str, str, object]] = []
        self._lock = threading.Lock()
        store.watch(self._journal)

    def _journal(self, event: str, kind: str, obj) -> None:
        with self._lock:
            self.events.append(
                (self.store.mutations, event, kind, serial.encode(obj))
            )
            if len(self.events) > 100_000:
                del self.events[:50_000]

    def since(self, cursor: int):
        with self._lock:
            return [e for e in self.events if e[0] > cursor]


class _Handler(BaseHTTPRequestHandler):
    server_version = "karpenter-fake-apiserver/1"
    api: ApiServer

    def log_message(self, *args) -> None:  # quiet
        pass

    def _send(self, code: int, payload) -> None:
        send_body(self, code, json.dumps(payload).encode())

    def _body(self):
        raw = read_body(self)
        return json.loads(raw) if raw else None

    # -- verbs -------------------------------------------------------------

    def do_GET(self) -> None:
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        store = self.api.store
        try:
            if parts == ["watch"]:
                cursor = int(parse_qs(url.query).get("since", ["0"])[0])
                events = self.api.since(cursor)
                self._send(200, {
                    "cursor": store.mutations,
                    "events": [
                        {"rv": rv, "event": ev, "kind": kind, "object": obj}
                        for rv, ev, kind, obj in events
                    ],
                })
            elif parts == ["healthz"]:
                self._send(200, {"ok": True})
            elif len(parts) == 2 and parts[0] == "apis":
                method = _LIST_KINDS.get(parts[1])
                if method is None:
                    return self._send(404, {"error": f"unknown kind {parts[1]}"})
                objs = getattr(store, method)()
                self._send(200, {"items": [serial.encode(o) for o in objs]})
            elif len(parts) == 4 and parts[0] == "apis":
                cls = _get_kinds().get(parts[1])
                if cls is None:
                    return self._send(404, {"error": f"unknown kind {parts[1]}"})
                obj = store.get(cls, parts[3], parts[2])
                if obj is None:
                    return self._send(404, {"error": "not found"})
                self._send(200, serial.encode(obj))
            elif parts[:1] == ["nodes-by-provider-id"]:
                pid = parse_qs(url.query).get("id", [""])[0]
                obj = store.get_node_by_provider_id(pid)
                if obj is None:
                    return self._send(404, {"error": "not found"})
                self._send(200, serial.encode(obj))
            else:
                self._send(404, {"error": f"bad path {url.path}"})
        except Exception as e:  # pragma: no cover - defensive
            self._send(500, {"error": repr(e)})

    def do_POST(self) -> None:
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        store = self.api.store
        try:
            if parts and parts[0] == "apis":
                obj = serial.decode(self._body())
                created = store.create(obj)
                self._send(201, serial.encode(created))
            elif parts == ["bind"]:
                body = self._body()
                from karpenter_core_tpu.api.objects import Pod

                pod = store.get(
                    Pod, body["name"], body.get("namespace", "default")
                )
                if pod is None:
                    return self._send(404, {"error": "pod not found"})
                store.bind(pod, body["node_name"])
                self._send(200, serial.encode(pod))
            elif parts == ["evict"]:
                body = self._body()
                from karpenter_core_tpu.api.objects import Pod

                pod = store.get(
                    Pod, body["name"], body.get("namespace", "default")
                )
                if pod is None:
                    return self._send(404, {"error": "pod not found"})
                store.evict(pod)
                self._send(200, {"evicted": True})
            else:
                self._send(404, {"error": "bad path"})
        except ConflictError as e:
            self._send(409, {"error": str(e)})
        except NotFoundError as e:
            self._send(404, {"error": str(e)})
        except TooManyRequestsError as e:
            self._send(429, {"error": str(e)})
        except Exception as e:  # pragma: no cover
            self._send(500, {"error": repr(e)})

    def do_PUT(self) -> None:
        try:
            obj = serial.decode(self._body())
            updated = self.api.store.update(obj)
            self._send(200, serial.encode(updated))
        except ConflictError as e:
            self._send(409, {"error": str(e)})
        except NotFoundError as e:
            self._send(404, {"error": str(e)})
        except Exception as e:  # pragma: no cover
            self._send(500, {"error": repr(e)})

    def do_DELETE(self) -> None:
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        store = self.api.store
        try:
            cls = _get_kinds().get(parts[1]) if len(parts) == 4 else None
            if cls is None:
                return self._send(404, {"error": "bad path"})
            obj = store.get(cls, parts[3], parts[2])
            if obj is None:
                raise NotFoundError(f"{parts[1]}/{parts[3]}")
            store.delete(obj)
            self._send(200, {"deleted": True})
        except NotFoundError as e:
            self._send(404, {"error": str(e)})
        except Exception as e:  # pragma: no cover
            self._send(500, {"error": repr(e)})


def serve(port: int, store: KubeStore = None) -> ThreadingHTTPServer:
    """Start serving on 127.0.0.1:port; returns the server (caller joins
    or shuts down). Port 0 picks a free port (server.server_address)."""
    api = ApiServer(store or KubeStore())
    handler = type("BoundHandler", (_Handler,), {"api": api})
    httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
    return httpd


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8123)
    args = ap.parse_args()
    httpd = serve(args.port)
    print(f"listening on {httpd.server_address[0]}:{httpd.server_address[1]}",
          flush=True)
    httpd.serve_forever()


if __name__ == "__main__":
    main()
