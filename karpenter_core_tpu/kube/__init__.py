from karpenter_core_tpu.kube.store import KubeStore

__all__ = ["KubeStore"]
