"""JSON object codec for the API surface — the wire format of the HTTP
apiserver pair (kube/httpserver.py + kube/httpclient.py).

The reference's objects cross its process boundary as CRD JSON validated
by generated OpenAPI schemas (pkg/apis/crds/); here the API types are
Python dataclasses, so the codec is a tagged dataclass walker: every
dataclass value encodes as {"!t": <registered type name>, <field>: ...},
tuples/sets/frozensets get container tags (they matter — frozen dataclass
fields must stay hashable), and the two non-dataclass carriers
(ConditionSet, the dict-subclass Limits) get explicit handlers. No
pickling anywhere — the registry below is the closed world of decodable
types, so a malicious peer cannot instantiate arbitrary classes.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

from karpenter_core_tpu.api import nodeclaim as _nodeclaim
from karpenter_core_tpu.api import nodepool as _nodepool
from karpenter_core_tpu.api import objects as _objects
from karpenter_core_tpu.api.duration import NillableDuration
from karpenter_core_tpu.api.status import Condition, ConditionSet

_TYPE_KEY = "!t"


def _registry() -> Dict[str, type]:
    reg: Dict[str, type] = {}
    for mod in (_objects, _nodepool, _nodeclaim):
        for name in dir(mod):
            cls = getattr(mod, name)
            if isinstance(cls, type) and dataclasses.is_dataclass(cls):
                reg[cls.__name__] = cls
    reg["NillableDuration"] = NillableDuration
    reg["Condition"] = Condition
    return reg


REGISTRY = _registry()
_NAMES = {cls: name for name, cls in REGISTRY.items()}


def encode(value: Any) -> Any:
    """Python object -> JSON-compatible structure."""
    if isinstance(value, ConditionSet):
        return {
            _TYPE_KEY: "ConditionSet",
            "types": list(value._types),
            "conditions": [encode(c) for c in value.all()],
        }
    if isinstance(value, _nodepool.Limits):
        return {_TYPE_KEY: "Limits", "items": dict(value)}
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        name = _NAMES.get(type(value))
        if name is None:
            raise TypeError(f"unregistered type {type(value).__name__}")
        out = {_TYPE_KEY: name}
        for f in dataclasses.fields(value):
            out[f.name] = encode(getattr(value, f.name))
        return out
    if isinstance(value, dict):
        return {k: encode(v) for k, v in value.items()}
    if isinstance(value, tuple):
        return {_TYPE_KEY: "!tuple", "items": [encode(v) for v in value]}
    if isinstance(value, frozenset):
        # tagged separately from set: frozen dataclass fields must decode
        # back hashable (a plain set would TypeError on first hash)
        return {
            _TYPE_KEY: "!frozenset",
            "items": sorted(encode(v) for v in value),
        }
    if isinstance(value, set):
        return {_TYPE_KEY: "!set", "items": sorted(encode(v) for v in value)}
    if isinstance(value, list):
        return [encode(v) for v in value]
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    raise TypeError(f"cannot encode {type(value).__name__}")


def decode(value: Any) -> Any:
    """JSON structure -> Python object (closed-world types only)."""
    if isinstance(value, list):
        return [decode(v) for v in value]
    if not isinstance(value, dict):
        return value
    tag = value.get(_TYPE_KEY)
    if tag is None:
        return {k: decode(v) for k, v in value.items()}
    if tag == "!tuple":
        return tuple(decode(v) for v in value["items"])
    if tag == "!set":
        return set(decode(v) for v in value["items"])
    if tag == "!frozenset":
        return frozenset(decode(v) for v in value["items"])
    if tag == "ConditionSet":
        cs = ConditionSet(*value.get("types", []))
        for c in decode(value.get("conditions", [])):
            cs._conditions[c.type] = c
        return cs
    if tag == "Limits":
        lim = _nodepool.Limits()
        lim.update(value.get("items", {}))
        return lim
    cls = REGISTRY.get(tag)
    if cls is None:
        raise TypeError(f"unknown wire type {tag!r}")
    # construct WITHOUT __init__/__post_init__: the wire already carries
    # the full derived state (e.g. Pod.resource_requests with overhead
    # folded in) — re-running derivation would re-apply overhead on every
    # round trip, inflating requests once per create/update/list hop
    obj = cls.__new__(cls)
    for f in dataclasses.fields(cls):
        if f.name in value:
            v = decode(value[f.name])
        elif f.default is not dataclasses.MISSING:
            v = f.default
        elif f.default_factory is not dataclasses.MISSING:
            v = f.default_factory()
        else:
            v = None
        object.__setattr__(obj, f.name, v)
    return obj


def sync_into(dest: Any, src: Any) -> None:
    """Copy src's dataclass fields into dest in place — how the client
    reflects server-assigned state (resourceVersion, timestamps, bind
    results) back into the caller's object, the way client-go decodes the
    response body into the passed object."""
    for f in dataclasses.fields(dest):
        setattr(dest, f.name, getattr(src, f.name))
