"""The client seam: what every controller types against.

The reference's controllers take a controller-runtime ``client.Client``
bound to a real kube-apiserver (operator.go:105-206); this framework's
controllers take a ``KubeClient``. ``kube.store.KubeStore`` is the
in-memory implementation (envtest's role, used by tests and benches); an
adapter over the kubernetes Python client satisfies the same protocol to
point the identical controller stack at a real apiserver — the structural
seam VERDICT r3 called out as the path off the in-memory store.

The protocol is runtime-checkable so conformance is testable; controllers
already duck-type, so any implementation with this surface drops in.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Protocol, runtime_checkable


@runtime_checkable
class KubeClient(Protocol):
    # -- CRUD (apiserver verbs) -------------------------------------------

    def create(self, obj) -> object: ...

    def get(self, cls, name: str, namespace: str = "default") -> Optional[object]: ...

    def update(self, obj) -> object: ...

    def delete(self, obj) -> None: ...

    # -- watch (the informer seam) ----------------------------------------

    def watch(self, fn: Callable[[str, str, object], None]) -> None: ...

    # -- typed listings ---------------------------------------------------

    def list_pods(self) -> List[object]: ...

    def list_nodes(self) -> List[object]: ...

    def list_nodeclaims(self) -> List[object]: ...

    def list_nodepools(self) -> List[object]: ...

    def list_daemonsets(self) -> List[object]: ...

    def list_volume_attachments(self) -> List[object]: ...

    def list_pdbs(self) -> List[object]: ...

    def get_node_by_provider_id(self, provider_id: str) -> Optional[object]: ...

    # -- pod subresources --------------------------------------------------

    def bind(self, pod, node_name: str) -> None: ...

    def evict(self, pod) -> None: ...
