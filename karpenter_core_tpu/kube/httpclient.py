"""HTTP KubeClient — the second implementation of the client seam.

Speaks the kube/httpserver.py protocol with stdlib http.client only, and
satisfies kube/client.py's KubeClient protocol: the SAME controller stack
that runs over the in-memory KubeStore runs over this client against an
apiserver in another process (tests/test_client_conformance.py +
tests/test_e2e_http.py prove it). Semantics mapping:

* create/update sync the server-assigned fields (resourceVersion,
  timestamps, bind results) back into the caller's object, the way
  client-go decodes the response into the passed struct;
* 404 -> NotFoundError, 409 -> ConflictError, 429 -> TooManyRequestsError
  (the PDB eviction contract, eviction.go:176);
* watch is a resource-version-cursored pull: the client drains the
  server's event feed after every write it issues (so self-originated
  events stay ordered like the store's synchronous notify) and on every
  poll()/list; external writers surface at the next drain — the informer
  resync model, not a long-lived stream, which keeps the client loop
  single-threaded like the rest of the framework.
"""
from __future__ import annotations

import http.client
import json
import time
from typing import Callable, List, Optional

from karpenter_core_tpu.kube import serial
from karpenter_core_tpu.kube.store import (
    ConflictError,
    NotFoundError,
    TooManyRequestsError,
)

_PLURALS = {
    "Pod": "pods",
    "Node": "nodes",
    "NodeClaim": "nodeclaims",
    "NodePool": "nodepools",
    "DaemonSet": "daemonsets",
    "VolumeAttachment": "volumeattachments",
    "PodDisruptionBudget": "poddisruptionbudgets",
    "PersistentVolumeClaim": "persistentvolumeclaims",
    "PersistentVolume": "persistentvolumes",
    "StorageClass": "storageclasses",
    "CSINode": "csinodes",
}
_NAMESPACED = {"Pod", "PersistentVolumeClaim", "PodDisruptionBudget",
               "DaemonSet"}


def _ns(kind: str, obj) -> str:
    return obj.metadata.namespace if kind in _NAMESPACED else "default"


# transient statuses a GET/LIST may retry through: apiserver overload (429)
# and gateway/server-side blips (5xx). Writes are NOT retried — a timed-out
# create/update may have landed, and replaying it is not idempotent.
_RETRYABLE_STATUSES = (429, 500, 502, 503, 504)
GET_RETRIES = 3
GET_RETRY_BACKOFF = 0.05


class HttpKubeClient:
    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 30.0,
        get_retries: int = GET_RETRIES,
        retry_backoff: float = GET_RETRY_BACKOFF,
    ):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.get_retries = get_retries
        self.retry_backoff = retry_backoff
        self._sleep = time.sleep  # injectable for tests
        self._watchers: List[Callable[[str, str, object], None]] = []
        self._cursor = 0
        self.mutations = 0  # event count; run_until_idle's idle signal

    # -- transport ---------------------------------------------------------

    def _do_request(self, method: str, path: str, payload=None):
        """One wire round-trip: (status, decoded body)."""
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout
        )
        try:
            body = None if payload is None else json.dumps(payload)
            conn.request(
                method, path, body=body,
                headers={"Content-Type": "application/json"} if body else {},
            )
            resp = conn.getresponse()
            data = json.loads(resp.read() or b"null")
        finally:
            conn.close()
        return resp.status, data

    def _request(self, method: str, path: str, payload=None):
        # bounded retry with exponential backoff for idempotent reads on
        # transient 429/5xx (client-go's rest client retries the same set);
        # everything else surfaces on the first response
        attempts = self.get_retries + 1 if method == "GET" else 1
        for attempt in range(attempts):
            status, data = self._do_request(method, path, payload)
            if (
                status in _RETRYABLE_STATUSES
                and attempt < attempts - 1
            ):
                self._sleep(self.retry_backoff * (2 ** attempt))
                continue
            break
        if status == 404:
            raise NotFoundError(str((data or {}).get("error", path)))
        if status == 409:
            raise ConflictError(str((data or {}).get("error", path)))
        if status == 429:
            raise TooManyRequestsError(str((data or {}).get("error", path)))
        if status >= 400:
            raise RuntimeError(f"{method} {path}: {status} {data}")
        return data

    # -- watch -------------------------------------------------------------

    def watch(self, fn: Callable[[str, str, object], None]) -> None:
        self._watchers.append(fn)

    def poll(self) -> int:
        """Drain the server's event feed; dispatch to watchers. Returns the
        number of events seen (drives mutations/idle detection)."""
        data = self._request("GET", f"/watch?since={self._cursor}")
        events = data.get("events", [])
        self._cursor = data.get("cursor", self._cursor)
        for e in events:
            self.mutations += 1
            obj = serial.decode(e["object"])
            for fn in self._watchers:
                fn(e["event"], e["kind"], obj)
        return len(events)

    # -- CRUD --------------------------------------------------------------

    def create(self, obj) -> object:
        kind = type(obj).__name__
        fresh = serial.decode(self._request(
            "POST", f"/apis/{_PLURALS[kind]}", serial.encode(obj)
        ))
        serial.sync_into(obj, fresh)
        self.poll()
        return obj

    def get(self, cls, name: str, namespace: str = "default"):
        kind = cls.__name__
        try:
            data = self._request(
                "GET", f"/apis/{_PLURALS[kind]}/{namespace}/{name}"
            )
        except NotFoundError:
            return None
        return serial.decode(data)

    def update(self, obj) -> object:
        kind = type(obj).__name__
        fresh = serial.decode(self._request(
            "PUT",
            f"/apis/{_PLURALS[kind]}/{_ns(kind, obj)}/{obj.metadata.name}",
            serial.encode(obj),
        ))
        serial.sync_into(obj, fresh)
        self.poll()
        return obj

    def delete(self, obj) -> None:
        kind = type(obj).__name__
        self._request(
            "DELETE",
            f"/apis/{_PLURALS[kind]}/{_ns(kind, obj)}/{obj.metadata.name}",
        )
        self.poll()

    # -- typed listings ----------------------------------------------------

    def _list(self, plural: str) -> List[object]:
        data = self._request("GET", f"/apis/{plural}")
        return [serial.decode(o) for o in data.get("items", [])]

    def list_pods(self):
        return self._list("pods")

    def list_nodes(self):
        return self._list("nodes")

    def list_nodeclaims(self):
        return self._list("nodeclaims")

    def list_nodepools(self):
        return self._list("nodepools")

    def list_daemonsets(self):
        return self._list("daemonsets")

    def list_volume_attachments(self):
        return self._list("volumeattachments")

    def list_pdbs(self):
        return self._list("poddisruptionbudgets")

    def get_node_by_provider_id(self, provider_id: str) -> Optional[object]:
        try:
            data = self._request(
                "GET", f"/nodes-by-provider-id?id={provider_id}"
            )
        except NotFoundError:
            return None
        return serial.decode(data)

    # -- pod subresources --------------------------------------------------

    def bind(self, pod, node_name: str) -> None:
        fresh = serial.decode(self._request("POST", "/bind", {
            "name": pod.metadata.name,
            "namespace": pod.metadata.namespace,
            "node_name": node_name,
        }))
        serial.sync_into(pod, fresh)
        self.poll()

    def evict(self, pod) -> None:
        self._request("POST", "/evict", {
            "name": pod.metadata.name,
            "namespace": pod.metadata.namespace,
        })
        self.poll()
