"""In-memory kube-apiserver stand-in.

The reference runs against a real apiserver (envtest for unit suites,
pkg/test/environment.go:60-80; kind for e2e). This framework is
self-contained: the store plays the apiserver's role for the controller
stack, with the same contracts the controllers rely on —

* finalizer-gated deletion: delete() stamps deletion_timestamp and keeps
  the object until the last finalizer is removed;
* resource_version bumping on every write (stale-write detection);
* watch callbacks (the informer seam, reference pkg/controllers/state/informer/);
* pod eviction that returns the pod to Pending instead of deleting it —
  standing in for the ReplicaSet controller recreating an evicted replica,
  so drain/consolidation flows are closed-loop without a workload
  controller.
"""
from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional

from karpenter_core_tpu.api.nodeclaim import NodeClaim
from karpenter_core_tpu.api.nodepool import NodePool
from karpenter_core_tpu.api.objects import (
    POD_PENDING,
    POD_RUNNING,
    CSINode,
    DaemonSet,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
    StorageClass,
    VolumeAttachment,
)

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

_KINDS = {
    Pod: "Pod",
    Node: "Node",
    NodeClaim: "NodeClaim",
    NodePool: "NodePool",
    DaemonSet: "DaemonSet",
    PersistentVolumeClaim: "PersistentVolumeClaim",
    PersistentVolume: "PersistentVolume",
    StorageClass: "StorageClass",
    CSINode: "CSINode",
    VolumeAttachment: "VolumeAttachment",
    PodDisruptionBudget: "PodDisruptionBudget",
}

# namespaced kinds key by namespace/name
_NAMESPACED = {"Pod", "PersistentVolumeClaim", "PodDisruptionBudget"}


class ConflictError(Exception):
    """Stale resource_version on update (optimistic-lock conflict)."""


class NotFoundError(Exception):
    pass


class TooManyRequestsError(Exception):
    """Eviction blocked by a PodDisruptionBudget (the apiserver's 429)."""


def _kind_of(obj) -> str:
    for cls, kind in _KINDS.items():
        if isinstance(obj, cls):
            return kind
    raise TypeError(f"unknown object kind: {type(obj)}")


def _key_of(kind: str, obj) -> str:
    if kind in _NAMESPACED:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"
    return obj.metadata.name


class KubeStore:
    def __init__(self, clock=None):
        from karpenter_core_tpu.utils.clock import Clock

        self.clock = clock or Clock()
        self._objects: Dict[str, Dict[str, object]] = {k: {} for k in _KINDS.values()}
        self._nodes_by_pid: Dict[str, Node] = {}
        self._rv = itertools.count(1)
        self._watchers: List[Callable[[str, str, object], None]] = []
        self.mutations = 0  # cheap idle detection for reconcile loops

    # -- watch ------------------------------------------------------------

    def watch(self, fn: Callable[[str, str, object], None]) -> None:
        """fn(event, kind, obj); fired synchronously on every write."""
        self._watchers.append(fn)

    def _notify(self, event: str, kind: str, obj) -> None:
        if kind == "Node" and getattr(obj, "provider_id", ""):
            if event == DELETED:
                self._nodes_by_pid.pop(obj.provider_id, None)
            else:
                self._nodes_by_pid[obj.provider_id] = obj
        self.mutations += 1
        for fn in self._watchers:
            fn(event, kind, obj)

    # -- CRUD -------------------------------------------------------------

    def create(self, obj) -> object:
        kind = _kind_of(obj)
        key = _key_of(kind, obj)
        if key in self._objects[kind]:
            raise ConflictError(f"{kind} {key} already exists")
        obj.metadata.resource_version = next(self._rv)
        if not obj.metadata.creation_timestamp:
            obj.metadata.creation_timestamp = self.clock.now()
        self._objects[kind][key] = obj
        self._notify(ADDED, kind, obj)
        return obj

    def get(self, cls, name: str, namespace: str = "default"):
        kind = _KINDS[cls]
        key = f"{namespace}/{name}" if kind in _NAMESPACED else name
        return self._objects[kind].get(key)

    def update(self, obj) -> object:
        kind = _kind_of(obj)
        key = _key_of(kind, obj)
        stored = self._objects[kind].get(key)
        if stored is None:
            raise NotFoundError(f"{kind} {key}")
        if (
            stored is not obj
            and obj.metadata.resource_version != stored.metadata.resource_version
        ):
            raise ConflictError(
                f"{kind} {key}: stale resource_version "
                f"{obj.metadata.resource_version} != {stored.metadata.resource_version}"
            )
        obj.metadata.resource_version = next(self._rv)
        self._objects[kind][key] = obj
        self._notify(MODIFIED, kind, obj)
        # finalizer-gated removal completes on the update that clears the
        # last finalizer
        if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
            self._remove(kind, key, obj)
        return obj

    def delete(self, obj) -> None:
        kind = _kind_of(obj)
        key = _key_of(kind, obj)
        existing = self._objects[kind].get(key)
        if existing is None:
            raise NotFoundError(f"{kind} {key}")
        if existing.metadata.finalizers:
            if existing.metadata.deletion_timestamp is None:
                existing.metadata.deletion_timestamp = self.clock.now()
                existing.metadata.resource_version = next(self._rv)
                self._notify(MODIFIED, kind, existing)
            return
        self._remove(kind, key, existing)

    def _remove(self, kind: str, key: str, obj) -> None:
        self._objects[kind].pop(key, None)
        self._notify(DELETED, kind, obj)
        # a deleted pod releases its volume attachments like the CSI driver
        # would (evict() handles the graceful path; this covers force
        # deletes, e.g. TGP-expired drains — without it the node's
        # detach-wait would block forever)
        if kind == "Pod" and obj.node_name:
            self._detach_unreferenced(obj, obj.node_name)

    # -- typed listings ---------------------------------------------------

    def list_pods(self) -> List[Pod]:
        return list(self._objects["Pod"].values())

    def list_nodes(self) -> List[Node]:
        return list(self._objects["Node"].values())

    def list_nodeclaims(self) -> List[NodeClaim]:
        return list(self._objects["NodeClaim"].values())

    def list_nodepools(self) -> List[NodePool]:
        return list(self._objects["NodePool"].values())

    def list_daemonsets(self) -> List[DaemonSet]:
        return list(self._objects["DaemonSet"].values())

    def get_node_by_provider_id(self, provider_id: str) -> Optional[Node]:
        return self._nodes_by_pid.get(provider_id)

    def list_volume_attachments(self) -> List[VolumeAttachment]:
        return list(self._objects["VolumeAttachment"].values())

    def list_pdbs(self) -> List[PodDisruptionBudget]:
        return list(self._objects["PodDisruptionBudget"].values())

    # -- pod verbs --------------------------------------------------------

    def bind(self, pod: Pod, node_name: str) -> None:
        """kube-scheduler Binding subresource stand-in. Bound PVs grow a
        VolumeAttachment (the attach-detach controller's role); detach on
        unbind is immediate unless a test injects slow-CSI attachments."""
        pod.node_name = node_name
        pod.phase = POD_RUNNING
        self.update(pod)
        for pv_name, driver in self._bound_pvs(pod):
            va_name = f"va-{node_name}-{pv_name}"
            if self.get(VolumeAttachment, va_name) is None:
                from karpenter_core_tpu.api.objects import ObjectMeta

                self.create(
                    VolumeAttachment(
                        metadata=ObjectMeta(name=va_name),
                        attacher=driver,
                        node_name=node_name,
                        pv_name=pv_name,
                    )
                )

    def _bound_pvs(self, pod: Pod):
        from karpenter_core_tpu.scheduling.volumeusage import pvc_name_for

        for vol in pod.volumes:
            claim_name = pvc_name_for(pod, vol)
            if claim_name is None:
                continue
            pvc = self.get(
                PersistentVolumeClaim, claim_name, pod.metadata.namespace
            )
            if pvc is None or not pvc.volume_name:
                continue
            pv = self.get(PersistentVolume, pvc.volume_name)
            yield pvc.volume_name, (pv.csi_driver if pv else "")

    def _detach_unreferenced(self, pod: Pod, node_name: str) -> None:
        """Remove VolumeAttachments for PVs no pod on the node still uses."""
        if not node_name or not pod.volumes:
            return
        still_used = set()
        for p in self._objects["Pod"].values():
            if p.node_name == node_name and p is not pod:
                still_used.update(name for name, _ in self._bound_pvs(p))
        for pv_name, _ in self._bound_pvs(pod):
            if pv_name in still_used:
                continue
            va = self.get(VolumeAttachment, f"va-{node_name}-{pv_name}")
            if va is not None:
                self.delete(va)

    def evict(self, pod: Pod) -> None:
        """Eviction API stand-in: PDB-gated like the apiserver (429 when a
        budget has no disruptions left). A replicated workload's pod returns
        to Pending (ReplicaSet recreation folded in); bare pods are
        deleted."""
        if pod.is_mirror or pod.is_daemonset:
            return
        key = _key_of("Pod", pod)
        if key not in self._objects["Pod"]:
            raise NotFoundError(f"Pod {key}")
        if self._objects["PodDisruptionBudget"]:
            from karpenter_core_tpu.utils.pdb import Limits

            blocking = Limits.from_kube(self).blocking_pdb(pod)
            if blocking is not None:
                raise TooManyRequestsError(
                    f"eviction of {key} blocked by pdb {blocking}"
                )
        prior_node = pod.node_name
        if pod.metadata.owner_references:
            pod.node_name = ""
            pod.phase = POD_PENDING
            self.update(pod)
        else:
            self.delete(pod)
        self._detach_unreferenced(pod, prior_node)
