"""The TPU provisioning solver — flagship model.

Drop-in counterpart of the greedy host scheduler
(controllers/provisioning/scheduling/scheduler.py): same inputs (nodepools,
instance-type catalog, existing nodes, pending pods), same Results shape,
but the FFD loop runs on device as a class-batched scan (ops/ffd.py) after
feasibility is precomputed as batched matmuls (ops/masks.py).

Pipeline per solve:
 1. host: pods → equivalence classes, sorted cpu/memory-descending
    (queue.go:76-112 ordering, lifted to classes)
 2. host: snapshot encode over a closed-world vocab (solver/snapshot.py)
 3. device: class×IT / class×template compatibility + fresh-node viability
 4. device: FFD scan over classes → per-slot take counts
 5. host: decode — merge each slot's class groups through the exact host
    algebra (Requirements.add + filter_instance_types), yielding the same
    InFlightNodeClaim objects the greedy path produces
 6. host: relaxation outer loop re-runs 1-5 for still-unschedulable pods
    (preferences.go:38-57)

NodePool resource limits are enforced exactly at claim-creation time
(provision() drops over-limit claims and errors their pods — no silent
livelock); the device solve itself does not model limits because a
per-pool budget cannot spill a class across templates the way the greedy
loop does (place_pod tries the next template when one pool's limit is
exhausted), and a budget without spill falsely errors schedulable pods.
The host-fallback path passes the pool's remaining resources through, so
fallback placements respect limits exactly like greedy.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodepool import NodePool
from karpenter_core_tpu.api.objects import Pod, Taint
from karpenter_core_tpu.cloudprovider.types import InstanceType
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
    ExistingNodeSim,
    IncompatibleError,
    InFlightNodeClaim,
    SimNode,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.nodeclaimtemplate import (
    NodeClaimTemplate,
    filter_instance_types,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.preferences import (
    Preferences,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.queue import (
    by_cpu_and_memory_descending,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
    Results,
    _daemon_compatible,
    node_daemon_pods,
    place_pod,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
    TYPE_ANTI_AFFINITY,
    TYPE_SPREAD,
    Topology,
    domain_universe,
    has_topology_constraints,
)
from karpenter_core_tpu.ops import gangsched
from karpenter_core_tpu.ops import masks as mops
from karpenter_core_tpu.ops import pallas_ffd
from karpenter_core_tpu.ops import relax as relax_ops
from karpenter_core_tpu.ops import topoplan
from karpenter_core_tpu.parallel import mesh as pmesh
from karpenter_core_tpu.ops.ffd import (
    BIG,
    BIGI,
    RANK_NONE,
    ClassStep,
    FFDStatics,
    SlotState,
    aggregate_takes,
    aggregate_takes_batched,
    ffd_solve,
    ffd_solve_batched_donated,
    ffd_solve_donated,
)
from karpenter_core_tpu.scheduling import Requirement, Requirements, Taints
from karpenter_core_tpu.solver import gangs as gangmod
from karpenter_core_tpu.solver.snapshot import PodClass, group_pods
from karpenter_core_tpu.solver.vocab import (
    EntityMasks,
    GT_NONE,
    LT_NONE,
    decode_requirements,
)
from karpenter_core_tpu.utils import resources as resutil


# Densification deferral knobs (see _decode_topo): fresh topology slots at
# or below DENSIFY_THRESHOLD x median pod count drain through the host
# repair path, capped at DENSIFY_CAP of the fresh slots AND at
# DENSIFY_POD_BUDGET total pods per solve (the repair is ~ms/pod of host
# algebra, so the budget bounds the decode-time cost at any scale).
# Deliberately conservative: the pass exists to recover genuinely sparse
# tail slots. Uniform thinness (every slot near the median, the cfg3-5k
# +5% equilibrium of class-batched packing) is NOT repairable this way —
# sweeping thresholds showed median-wide deferral either re-creates the
# same slots (spread/anti constraints force fresh hosts) or devolves into
# a full host re-solve at ~ms/pod.
DENSIFY_THRESHOLD = 0.5
DENSIFY_CAP = 0.125
DENSIFY_POD_BUDGET = 256


def _neutralize(masks: EntityMasks) -> EntityMasks:
    """Apply the neutral-where-undefined invariant required by ffd_step."""
    d = masks.defines
    return EntityMasks(
        mask=np.where(d[:, :, None], masks.mask, True),
        defines=d,
        concrete=np.where(d, masks.concrete, False),
        negative=np.where(d, masks.negative, True),
        gt=masks.gt,
        lt=masks.lt,
    )


def _tolerates_taints(tolerations, taints) -> bool:
    return all(any(tol.tolerates(t) for tol in tolerations) for t in taints)


def _bucket(n: int, lo: int = 8) -> int:
    """Next power of two (>= lo): device-array axes pad to bucketed sizes so
    repeated solves with drifting shapes (class counts, vocab growth, pod
    mixes) hit the jit cache instead of recompiling for seconds."""
    return max(lo, 1 << max(n - 1, 1).bit_length())


def _bucket_steps(n: int, lo: int = 8) -> int:
    """Half-octave bucket (… 8, 12, 16, 24, 32 …) for the SCAN STEP axis
    only. Scan length costs wall-clock linearly — a diverse 50k topology
    mix lands ~11.5k steps, and a pure power-of-two pad burns 40% of the
    kernel on inert steps — so the step axis trades one extra jit entry
    per octave for a <=33% (avg ~17%) pad ceiling. Tensor axes keep the
    pure power-of-two buckets: their padding costs memory, not scan
    iterations."""
    p = _bucket(n, lo)
    half = (p // 4) * 3
    if half >= lo and n <= half:
        return half
    return p


def _pad(a: np.ndarray, targets: dict, fill) -> np.ndarray:
    """Pad axes of a to targets {axis: size} with a constant fill."""
    widths = [(0, 0)] * a.ndim
    for axis, size in targets.items():
        widths[axis] = (0, max(size - a.shape[axis], 0))
    if all(w == (0, 0) for w in widths):
        return a
    return np.pad(a, widths, constant_values=fill)


def _same_template_gang_ids(classes, Cp: int):
    """[Cp] int32 gang index per class for gangs declaring same-template
    co-location (-1 outside any), plus the gang count — the gang_id input
    of ops/masks.gang_joint_templates. The flag ORs across members
    (solver/gangs.collect_gangs contract: any member asking binds the
    gang), so an unflagged class of a flagged gang is constrained too."""
    flagged = {
        g[0]
        for cls in classes
        if (g := getattr(cls, "gang", None)) is not None and g[3]
    }
    by_name: Dict[str, int] = {}
    gid = np.full((Cp,), -1, dtype=np.int32)
    for ci, cls in enumerate(classes):
        g = getattr(cls, "gang", None)
        if g is not None and g[0] in flagged:
            gid[ci] = by_name.setdefault(g[0], len(by_name))
    return gid, len(by_name)


class _SlotOverflow(Exception):
    """More slots needed than max_slots — caller doubles and retries."""


# one slot per pod is the true worst case; 1M slots is far past any
# realistic solve and bounds the doubling loop
_SLOT_HARD_CAP = 1 << 20


@dataclass
class _Prepared:
    vocab: object
    resource_names: List[str]
    catalog: List[InstanceType]
    class_masks: EntityMasks
    class_requests: np.ndarray  # [C, R]
    classes: List[PodClass]
    templates: List[NodeClaimTemplate]
    # DEVICE-RESIDENT until the post-scan fetch (jax.Array at BUCKETED
    # shapes): class_it [Cp, Tp], tmpl_ok [Cp, Sp], new_template/kstar [Cp]
    # (ops/masks.fresh_viability outputs). _solve_once swaps class_it for
    # the fetched numpy [Cp, T] right before decode — the only host reader.
    class_it: object
    tmpl_ok: object
    new_template: object
    kstar: object
    statics: FFDStatics
    init_state: SlotState
    exist_taint_ok: np.ndarray  # [C, N]
    existing_sims: List[ExistingNodeSim]
    n_slots: int
    topo: Topology
    plan: topoplan.TopoPlan
    smask: np.ndarray  # [C, K, V] strict (pod_domains) value masks
    # float64 decode twins, quantized to the device's integer units
    # (unclamped — float64 is exact to 2^53): every decode refit runs in
    # the SAME arithmetic regime as the kernel, so slots the kernel packed
    # exactly full are never rejected over raw-float drift (repeated raw
    # adds drift ~1e-13 at exact boundaries — the r4 50k-topology decode
    # cliff, where whole slots deferred to the per-pod host path).
    # Ceil-requests/floor-capacity stays conservative vs true decimal
    # quantities (k8s resource.Quantity is fixed-point, resources.go:28-66).
    it_alloc64q: np.ndarray  # [pad_T, R] float64 (floor-quantized)
    class_requests64q: np.ndarray  # [C, R] float64 (ceil-quantized)
    tmpl_overhead64q: np.ndarray  # [pad_S, R] float64 (ceil-quantized)
    off_avail_np: np.ndarray  # [pad_T, Z, CT] bool
    tmpl_it_np: np.ndarray  # [pad_S, pad_T] bool
    tmpl_mask_np: np.ndarray  # [pad_S, K, V] bool
    zone_kid: int
    ct_kid: int
    n_zones: int
    n_cts: int
    level_iters: int = 32
    # prepared-state reuse plumbing (PR 3): Cp is the bucketed class axis
    # the decision planes aggregate to; _batch is the prepared-cache entry
    # the per-class tensors came from (ClassStep device arrays are cached
    # on it by _class_steps); step_class is the device [Jp] step->class
    # index driving the on-device takes aggregation.
    n_classes_padded: int = 8
    _batch: dict = field(default_factory=dict)
    step_class: object = None
    # gangsched (ISSUE 10) — all None/empty for plain problems, so the
    # dispatch gate below them stays byte-parity with the pre-gang path.
    # gangs: GangSpecs fully on the device path (kernel-enforced); a gang
    # spanning a fallback class is excluded here and relies on the host
    # backstop (solver/gangs.enforce_atomicity). step_tier/step_gang are
    # device [Jp] rows aligned with the scanned ClassStep; gang_min is the
    # device [Gp] per-gang min-count; ev/ev_uids/ev_freed carry the
    # evictable-capacity planes and their host-side uid/request tables.
    gangs: list = field(default_factory=list)
    step_tier: object = None
    step_gang: object = None
    gang_min: object = None
    ev: object = None
    ev_uids: list = field(default_factory=list)
    ev_freed: list = field(default_factory=list)
    # relaxsolve (ISSUE 13): the candidate dispatch re-runs the FFD scan
    # from a FRESH init state (the baseline's was donated), so the
    # builder args are stashed here; tmpl_price_d is the [Sp] per-template
    # min node price the scored fallback ranks candidates with.
    init_args: tuple = None
    tmpl_price_d: object = None
    # topoaware (ISSUE 20): per-gang anchor domain ids into the fp entry's
    # RackPlan — None whenever the catalog carries no rack labels (the
    # subsystem's fully-disengaged parity default)
    topo_anchors: dict = None


# ---------------------------------------------------------------------------
# the kernel-dispatch seam (continuous cross-tenant batching, ISSUE 9)
#
# DeviceScheduler.solve runs as a generator that YIELDS one _KernelRequest
# per device dispatch; a driver answers each request with (final SlotState,
# takes-by-class, unplaced-by-class). The solo driver (_drive_solo) answers
# with the donating single-problem kernels — byte-for-byte the old solve
# path. The batch driver (solve_batch) interleaves N problems' generators,
# groups their outstanding requests by exact compile shape, and answers
# whole groups from ONE vmapped dispatch (ops/ffd.ffd_solve_batched) — the
# scheduler-gateway analogue of continuous batching in LLM serving.


@dataclass
class _KernelRequest:
    """One device dispatch, reified so a driver outside the generator can
    answer it — solo, or stacked into a multi-problem vmapped batch.

    ``kind`` selects the kernel family: ``"solve"`` (the FFD scan — the
    gang-atomic twin dispatches when gang_of_step is set) answered with
    (final state, takes_bc, unplaced_bc, seconds); ``"preempt"`` (the
    gangsched eviction pass over a FINISHED solve's state) answered with
    (extra_takes_bc, unplaced_bc', evicted [N, P], seconds); ``"relax"``
    (the relaxsolve assignment + rounding, ops/relax.relax_choose)
    answered with (new_template [Cp], kstar [Cp], n_changed, seconds)."""

    init_state: SlotState
    steps: ClassStep
    statics: FFDStatics
    level_iters: int
    step_class: object  # [Jp] int32 step -> class index
    num_classes: int  # Cp, the bucketed class axis (static)
    devices: int
    n_slots: int
    kind: str = "solve"
    # solver backend that issued this request ("ffd" | "relax"): a pure
    # shape_key component, so a relax problem's dispatches — including its
    # plain-FFD anytime baseline, which compiles to the *same* jit entry
    # an ffd problem's solve does — can never coalesce into an ffd
    # problem's vmapped batch (the kernel-seam half of the
    # codec.problem_bucket solver-mode component)
    mode: str = "ffd"
    # kernel backend that answers the FFD-scan dispatches ("xla" |
    # "pallas", ISSUE 18): like ``mode``, a pure shape_key component —
    # a pallas problem's dispatch must never coalesce into an xla
    # problem's vmapped batch (their fused/unfused kernels are different
    # jit entries even at identical tensor shapes). Gang, preempt, and
    # relax dispatches stay on the XLA kernels under either backend (the
    # fused port covers the FFD scan — ~85% of kernel_s); the field still
    # rides those requests so a mixed-backend fleet's buckets split
    # cleanly (the kernel-seam half of the solverd ``|k{kernel}`` bucket
    # suffix).
    backend: str = "xla"
    # gang-atomic solve (both None for plain problems — same kernels,
    # same jit entries, byte-identical results as pre-gang)
    # [Jp] int32 gang step index (gangmod.GANG_FREE outside any gang,
    # gangmod.GANG_FALLBACK_STRADDLING for host-enforced gangs)
    gang_of_step: object = None
    gang_min: object = None  # [Gp] int32 per-gang min-count
    # preemption pass inputs (kind == "preempt")
    step_tier: object = None  # [Jp] int32
    step_gang: object = None  # [Jp] int32
    unplaced: object = None  # [Jp] int32 still-unplaced per step
    ev: object = None  # ops/gangsched.EvPlanes
    node_rounds: int = gangsched.NODE_ROUNDS
    # relaxsolve assignment inputs (kind == "relax"): the ops/relax
    # constraint planes (viable, k_cs, k_node, podcost, counts, gang_id,
    # base_template, base_kstar, warm_template) plus the static
    # iteration/gang counts
    relax: tuple = None
    relax_iters: int = 0
    relax_gangs: int = 0

    def shape_key(self) -> tuple:
        """Exact compile-shape identity: requests with equal keys ride one
        vmapped dispatch (and equal-key dispatches at the same padded
        batch size share one jit entry). Every tensor axis is padded to a
        power-of-two bucket upstream (_bucket), so cross-tenant collisions
        are the common case by construction, not luck. The gang/preempt
        tensors join the leaf walk, so a gang problem can never coalesce
        into a plain problem's vmapped batch (their keys differ by the
        extra leaves even at equal state shapes) — the kernel-seam half of
        the codec.problem_bucket gang components."""
        leaves = jax.tree.leaves((
            self.init_state, self.steps, self.statics,
            self.gang_of_step, self.gang_min,
            self.step_tier, self.step_gang, self.unplaced, self.ev,
            self.relax,
        ))
        return (
            self.kind,
            self.mode,
            self.backend,
            tuple((tuple(x.shape), str(x.dtype)) for x in leaves),
            self.level_iters,
            self.num_classes,
            self.devices,
            self.node_rounds,
            self.relax_iters,
            self.relax_gangs,
        )


def _run_kernel_solo(req: _KernelRequest):
    """Answer one request with the donating single-problem kernels. The
    trailing element is this problem's kernel-dispatch seconds — the
    driver owns dispatch timing because a timer held open across the
    generator's yield would charge batch-mates' work to this problem."""
    t0 = time.perf_counter()
    if req.kind == "relax":
        nt, ks, changed = relax_ops.relax_choose(
            *req.relax, iters=req.relax_iters, num_gangs=req.relax_gangs
        )
        return nt, ks, int(changed), time.perf_counter() - t0
    if req.kind == "preempt":
        extra, m_left, evicted = gangsched.preempt_pass(
            req.init_state, req.steps, req.statics,
            req.step_tier, req.step_gang, req.unplaced, req.ev,
            node_rounds=req.node_rounds,
        )
        extra_bc, mleft_bc = aggregate_takes(
            extra, m_left, req.step_class, num_classes=req.num_classes
        )
        return extra_bc, mleft_bc, evicted, time.perf_counter() - t0
    if req.gang_of_step is not None:
        state, takes, unplaced = gangsched.gang_solve_donated(
            req.init_state, req.steps, req.statics,
            req.gang_of_step, req.gang_min, level_iters=req.level_iters,
        )
    elif req.backend == "pallas":
        init, steps, statics = req.init_state, req.steps, req.statics
        if req.devices > 1:
            # the pallas_call boundary is opaque to GSPMD: commit the
            # planes replicated (the sanctioned parallel.mesh route)
            # instead of letting XLA all-gather per fused step
            mesh = pmesh.slot_mesh(req.devices)
            init, steps, statics = jax.device_put(
                (init, steps, statics),
                pmesh.pallas_slot_shardings(mesh, (init, steps, statics)),
            )
        state, takes, unplaced = pallas_ffd.pallas_ffd_solve_donated(
            init, steps, statics, level_iters=req.level_iters,
        )
    else:
        state, takes, unplaced = ffd_solve_donated(
            req.init_state, req.steps, req.statics,
            level_iters=req.level_iters,
        )
    takes_bc, unplaced_bc = aggregate_takes(
        takes, unplaced, req.step_class, num_classes=req.num_classes
    )
    return state, takes_bc, unplaced_bc, time.perf_counter() - t0


def _drive_solo(gen):
    """Run one problem's solve generator to completion with direct
    (donating) kernel dispatches — the single-problem production path."""
    out = None
    while True:
        try:
            req = gen.send(out)
        except StopIteration as stop:
            return stop.value
        out = _run_kernel_solo(req)


def _stack_trees(trees):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


# batch-axis pad floor: padded batch sizes are powers of two (1, 2, 4, ...)
# so the jit cache holds at most log2(max_batch) entries per shape bucket
_BATCH_PAD_LO = 1


def _run_kernel_batched(reqs: List[_KernelRequest]):
    """Answer N equal-shape requests from ONE vmapped device dispatch.

    The problem axis pads to a power of two with copies of the first
    request's arrays (inert — their outputs are sliced off before anyone
    reads them), bounding jit-cache growth across arbitrary batch sizes.
    Returns (per-request (state, takes_bc, unplaced_bc) list, padded B).
    """
    head = reqs[0]
    B = len(reqs)
    t0 = time.perf_counter()
    Bp = _bucket(B, lo=_BATCH_PAD_LO)
    reqs_p = list(reqs) + [head] * (Bp - B)
    if head.kind == "relax":
        # the assignment planes carry no slot axis: stack the problem
        # axis, commit replicated on a multi-device mesh (the sanctioned
        # parallel.mesh route), one vmapped choose dispatch
        stacked = tuple(
            jnp.stack([r.relax[i] for r in reqs_p])
            for i in range(len(head.relax))
        )
        if head.devices > 1:
            mesh = pmesh.slot_mesh(head.devices)
            stacked = jax.device_put(
                stacked, pmesh.relax_plane_shardings(mesh, stacked)
            )
        nt_b, ks_b, changed_b = relax_ops.relax_choose_batched(
            *stacked, iters=head.relax_iters, num_gangs=head.relax_gangs
        )
        changed_h = jax.device_get(changed_b)
        share = (time.perf_counter() - t0) / B
        return [
            (nt_b[b], ks_b[b], int(changed_h[b]), share) for b in range(B)
        ], Bp
    state = _stack_trees([r.init_state for r in reqs_p])
    steps = _stack_trees([r.steps for r in reqs_p])
    statics = _stack_trees([r.statics for r in reqs_p])
    step_class = jnp.stack([r.step_class for r in reqs_p])
    mesh = repl = None
    if head.devices > 1:
        # re-commit the stacked trees to the slot mesh: problem axis
        # replicated, slot axis sharded (parallel/mesh batched specs) — a
        # bare stack of per-problem sharded planes would leave the layout
        # to XLA's whim per dispatch, breaking the PR 6 SPMD contract
        mesh = pmesh.slot_mesh(head.devices)
        repl = pmesh.replicated(mesh)
        state = jax.device_put(
            state, pmesh.batched_slot_shardings(mesh, state, head.n_slots)
        )
        steps = jax.device_put(
            steps, pmesh.batched_step_shardings(mesh, steps, head.n_slots)
        )
        statics = jax.device_put(statics, jax.tree.map(lambda _: repl, statics))
        step_class = jax.device_put(step_class, repl)
    if head.kind == "preempt":
        step_tier = jnp.stack([r.step_tier for r in reqs_p])
        step_gang = jnp.stack([r.step_gang for r in reqs_p])
        unplaced0 = jnp.stack([r.unplaced for r in reqs_p])
        ev = _stack_trees([r.ev for r in reqs_p])
        if mesh is not None:
            step_tier = jax.device_put(step_tier, repl)
            step_gang = jax.device_put(step_gang, repl)
            unplaced0 = jax.device_put(unplaced0, repl)
            ev = jax.device_put(
                ev,
                pmesh.batched_gang_plane_shardings(mesh, ev, head.n_slots),
            )
        extra_b, mleft_b, evicted_b = gangsched.preempt_pass_batched(
            state, steps, statics, step_tier, step_gang, unplaced0, ev,
            node_rounds=head.node_rounds,
        )
        extra_bc, mleft_bc = aggregate_takes_batched(
            extra_b, mleft_b, step_class, num_classes=head.num_classes
        )
        share = (time.perf_counter() - t0) / B
        return [
            (extra_bc[b], mleft_bc[b], evicted_b[b], share)
            for b in range(B)
        ], Bp
    if head.gang_of_step is not None:
        gang_of_step = jnp.stack([r.gang_of_step for r in reqs_p])
        gang_min = jnp.stack([r.gang_min for r in reqs_p])
        if mesh is not None:
            gang_of_step = jax.device_put(gang_of_step, repl)
            gang_min = jax.device_put(gang_min, repl)
        state_b, takes_b, unplaced_b = gangsched.gang_solve_batched_donated(
            state, steps, statics, gang_of_step, gang_min,
            level_iters=head.level_iters,
        )
    elif head.backend == "pallas":
        if mesh is not None:
            # opaque-to-GSPMD pallas boundary: re-commit the stacked
            # trees replicated (see _run_kernel_solo)
            state, steps, statics = jax.device_put(
                (state, steps, statics),
                pmesh.pallas_slot_shardings(mesh, (state, steps, statics)),
            )
        state_b, takes_b, unplaced_b = (
            pallas_ffd.pallas_ffd_solve_batched_donated(
                state, steps, statics, level_iters=head.level_iters
            )
        )
    else:
        state_b, takes_b, unplaced_b = ffd_solve_batched_donated(
            state, steps, statics, level_iters=head.level_iters
        )
    takes_bc, unplaced_bc = aggregate_takes_batched(
        takes_b, unplaced_b, step_class, num_classes=head.num_classes
    )
    # each member's kernel share is an equal split of the batched
    # dispatch wall (the vmapped scan does the same work per row)
    share = (time.perf_counter() - t0) / B
    outs = [
        (
            jax.tree.map(lambda x: x[b], state_b),  # noqa: B023
            takes_bc[b],
            unplaced_bc[b],
            share,
        )
        for b in range(B)
    ]
    return outs, Bp


def solve_batch(entries):
    """Solve N independent problems under ONE exclusive device window,
    coalescing compatible kernel dispatches into vmapped batches.

    ``entries``: ``[(scheduler, pods), ...]`` — one DISTINCT
    DeviceScheduler per problem (a scheduler carries per-solve mutable
    state and is not reentrant; the fleet gateway guarantees distinct
    problem fingerprints per batch, which maps to distinct cache entries).

    Every problem runs the identical per-problem pipeline as
    ``scheduler.solve(pods)`` — same host prepare, same decode, same
    relaxation loop, same verification — only equal-shape device
    dispatches are answered together. Problems whose shapes diverge
    (different buckets, or one needs an overflow-retry round the others
    don't) simply fall back to solo dispatches inside the same window.

    Failure is per-problem: a member whose dispatch or decode raises gets
    an ("error", exc) outcome while its batch-mates complete ("ok",
    Results). A failing VMAPPED dispatch (which cannot attribute blame)
    is retried solo per member, so the poisoned problem fails alone.

    Returns (outcomes, stats): outcomes aligned with entries; stats counts
    dispatches, batched problems, and batch-axis padding for the gateway's
    batch metrics.
    """
    if len({id(s) for s, _ in entries}) != len(entries):
        raise ValueError(
            "solve_batch requires a distinct DeviceScheduler per problem"
            " (schedulers are single-solve stateful)"
        )
    def _gen_for(scheduler, pods):
        if hasattr(scheduler, "_solve_gen"):
            return scheduler._solve_gen(pods)

        # duck-typed scheduler (test fakes, alternate backends): no kernel
        # seam to interleave, so it runs whole at its batch slot — a
        # zero-yield generator keeps the driver uniform
        def _compat():
            return scheduler.solve(pods)
            yield  # unreachable; makes _compat a generator

        return _compat()

    gens = []
    outcomes: List[Optional[tuple]] = [None] * len(entries)
    pending: Dict[int, _KernelRequest] = {}
    for i, (scheduler, pods) in enumerate(entries):
        gen = _gen_for(scheduler, pods)
        gens.append(gen)
        try:
            pending[i] = gen.send(None)
        except StopIteration as stop:
            outcomes[i] = ("ok", stop.value)
        except Exception as e:  # per-problem isolation
            outcomes[i] = ("error", e)
    stats = {
        "problems": len(entries),
        "dispatches": 0,
        "batched_dispatches": 0,
        "batched_problems": 0,
        "padded_rows": 0,
        "padded_total_rows": 0,
    }
    while pending:
        groups: Dict[tuple, List[int]] = {}
        for i in sorted(pending):
            groups.setdefault(pending[i].shape_key(), []).append(i)
        answers: Dict[int, tuple] = {}
        for idxs in groups.values():
            if len(idxs) == 1:
                i = idxs[0]
                stats["dispatches"] += 1
                try:
                    answers[i] = ("ok", _run_kernel_solo(pending[i]))
                except Exception as e:
                    answers[i] = ("error", e)
                continue
            stats["dispatches"] += 1
            try:
                outs, padded = _run_kernel_batched(
                    [pending[i] for i in idxs]
                )
            except Exception:
                # the vmapped dispatch failed as a unit — blame is
                # unattributable, so re-run each member solo INSIDE the
                # same device window: the poison fails alone, the rest
                # still solve
                for i in idxs:
                    stats["dispatches"] += 1
                    try:
                        answers[i] = ("ok", _run_kernel_solo(pending[i]))
                    except Exception as e:
                        answers[i] = ("error", e)
            else:
                stats["batched_dispatches"] += 1
                stats["batched_problems"] += len(idxs)
                stats["padded_rows"] += padded - len(idxs)
                stats["padded_total_rows"] += padded
                for i, out in zip(idxs, outs):
                    answers[i] = ("ok", out)
        nxt: Dict[int, _KernelRequest] = {}
        for i, (status, out) in answers.items():
            gen = gens[i]
            try:
                if status == "ok":
                    nxt[i] = gen.send(out)
                else:
                    # surface the kernel failure INSIDE the generator so
                    # its cleanup runs and the error lands per-problem
                    nxt[i] = gen.throw(out)
            except StopIteration as stop:
                outcomes[i] = ("ok", stop.value)
            except Exception as e:
                outcomes[i] = ("error", e)
        pending = nxt
    return outcomes, stats


class DeviceScheduler:
    """Same construction surface as the greedy Scheduler, device solve."""

    def __init__(
        self,
        nodepools: List[NodePool],
        instance_types: Dict[str, List[InstanceType]],
        existing_nodes: Optional[List[SimNode]] = None,
        daemonset_pods: Optional[List[Pod]] = None,
        max_slots: int = 256,
        topology: Optional[Topology] = None,
        unavailable_offerings: "frozenset | set" = frozenset(),
        devices: int = 1,
        verify: bool = True,
        recorder=None,
        solver_mode: str = "ffd",
        relax_iters: Optional[int] = None,
        relax_budget_s: Optional[float] = None,
        kernel_backend: str = "xla",
    ):
        # relaxsolve (ISSUE 13): "ffd" is the classic first-fit-decreasing
        # backend, byte-untouched; "relax" layers the convex-relaxation
        # template optimizer over the same scan (ops/relax.py) with the
        # FFD result as the scored/anytime fallback. relax_budget_s is
        # the wall budget (from solve start) after which relax work is
        # skipped and the FFD answer serves — the anytime contract.
        if solver_mode not in ("ffd", "relax"):
            raise ValueError(f"unknown solver mode {solver_mode!r}")
        self.solver_mode = solver_mode
        # kernel backend (ISSUE 18): "xla" is the classic lax.scan whose
        # per-step stages lower as separate XLA ops; "pallas" routes the
        # FFD-scan dispatches through the hand-fused per-class kernel
        # (ops/pallas_ffd.py) — byte-identical results, one fused VMEM-
        # resident invocation per class step. Orthogonal to solver_mode:
        # relax mode's FFD baseline/candidate scans ride the selected
        # backend too; gang/preempt/relax dispatches stay on XLA kernels.
        if kernel_backend not in ("xla", "pallas"):
            raise ValueError(f"unknown kernel backend {kernel_backend!r}")
        self.kernel_backend = kernel_backend
        self.relax_iters = (
            relax_iters
            if relax_iters is not None
            else relax_ops.DEFAULT_ITERS
        )
        self.relax_budget_s = relax_budget_s
        # incsolve warm start (ISSUE 16): {class signature -> nodepool
        # name} from the PackingLedger's prior accepted packing. Set by
        # solver/incremental before a solve; _relax_improve lowers it to
        # the per-class warm_template vector so the projected-gradient
        # loop starts at last round's vertex instead of the simplex
        # center. None (the default) keeps the kernel's cold start and is
        # bit-identical to pre-warm behavior.
        self._relax_warm: Optional[Dict] = None
        # ICE'd offerings project onto the catalog exactly like the greedy
        # path (apply_unavailable), so the host-side machinery — template
        # prefilter, decode refit, host fallback, price ordering — all see
        # the stockout; the device side additionally masks the offerings
        # tensor (off_avail in _prepare_with_vocab) so in-kernel zone/ct
        # viability excludes the stocked-out rows
        from karpenter_core_tpu.cloudprovider.types import apply_unavailable

        instance_types = apply_unavailable(instance_types, unavailable_offerings)
        self.unavailable_offerings = frozenset(unavailable_offerings)
        # multi-device solve (the pjit-over-ICI production path): with
        # devices > 1 every device array is committed to a 1-D slot mesh —
        # SlotState (and the per-step exist_taint_ok planes) land
        # PRE-SHARDED over the slot axis via _dev_slots, everything else
        # replicated via _dev — so the jit'd kernels (ops/ffd, ops/masks)
        # compile SPMD from the argument shardings and XLA collectives
        # carry the first-fit prefix sum and the class scan. devices<=0
        # means "all local devices"; requests clamp to what exists, so the
        # same config degrades to the single-device path on a 1-chip box.
        self.devices = pmesh.resolve_devices(devices)
        if self.devices > 1:
            self._mesh = pmesh.slot_mesh(self.devices)
            self._repl = pmesh.replicated(self._mesh)
        else:
            self._mesh = None
            self._repl = None
        # a supplied Topology carries cluster context (existing pods,
        # exclusions); its groups are rebuilt fresh each solve round, so only
        # the constructor inputs are kept
        self._topology_context = topology
        self.nodepools = sorted(nodepools, key=lambda n: (-n.spec.weight, n.name))
        self.instance_types = instance_types
        # initialized nodes first, then by name (scheduler.go:344-354) —
        # must match the greedy oracle's fill order
        self.existing_nodes = sorted(
            existing_nodes or [], key=lambda n: (not n.initialized, n.name)
        )
        self.daemonset_pods = list(daemonset_pods or [])
        self.max_slots = max_slots
        # NodePool limits minus existing usage (scheduler.go:85-88,336-340)
        self.remaining_resources: Dict[str, dict] = {
            np_.name: dict(np_.spec.limits)
            for np_ in self.nodepools
            if np_.spec.limits
        }
        for node in self.existing_nodes:
            if node.nodepool_name in self.remaining_resources:
                self.remaining_resources[node.nodepool_name] = resutil.subtract(
                    self.remaining_resources[node.nodepool_name],
                    node.capacity or node.available,
                )
        self.domains_universe = domain_universe(
            nodepools, instance_types, self.existing_nodes
        )

        tolerate_pns = any(
            t.effect == "PreferNoSchedule"
            for np_ in self.nodepools
            for t in np_.spec.template.taints
        )
        self.preferences = Preferences(tolerate_pns)

        self.templates: List[NodeClaimTemplate] = []
        for np_ in self.nodepools:
            nct = NodeClaimTemplate.from_nodepool(np_)
            nct.instance_type_options = filter_instance_types(
                instance_types.get(np_.name, []), nct.requirements, {}
            ).remaining
            if nct.instance_type_options:
                self.templates.append(nct)

        # daemon overhead per template (scheduler.go:358-364)
        self.daemon_overhead = [
            resutil.requests_for_pods(
                *[p for p in self.daemonset_pods if _daemon_compatible(nct, p)]
            )
            for nct in self.templates
        ]

        # -- prepared-state caches (PR 3 incremental re-solve) -------------
        # Everything encoded over a frozen vocab is a pure function of
        # (vocab fingerprint, entity): catalog/template/existing-node
        # tensors cache per fingerprint (_fp_cache), per-class rows cache
        # per (fingerprint, class signature) (_row_cache), and the fully
        # stacked class batch — including the device-resident ClassStep —
        # caches per (fingerprint, slot count, topology-plan digest, class
        # signature+count tuple) (_batch_cache). Relaxation rounds union
        # the prior round's vocab (_round_frozen) so spec-shrinking relaxes
        # keep the fingerprint and rebuild only the classes they mutated.
        self._catalog = None
        self._exist_label_reqs = None
        self._universe = None
        self._base_resources = None
        self._fp_ids: Dict[tuple, int] = {}
        self._fp_cache: Dict[int, dict] = {}
        self._row_cache: Dict[tuple, dict] = {}
        self._batch_cache: Dict[tuple, dict] = {}
        self._round_frozen = None
        # adaptive slot-axis sizing: warm solves start at a bucket sized
        # from the previous solve's observed usage instead of max_slots
        self._slots_hint: Optional[int] = None
        self._h2d_bytes = 0
        self._h2d_dev_bytes = 0
        self.last_phase_stats: Dict[str, float] = {}
        # host-side result verification (solver/verify.py): an independent
        # O(pods) constraint re-check over the final Results — the trust
        # anchor between the device kernels and NodeClaim creation. A
        # rejected result degrades THIS solve to the greedy host path
        # (metrics + Warning event via the recorder when one is wired).
        self.verify = verify
        self.recorder = recorder
        # built lazily ONCE: the verifier's setup (domain universe,
        # per-pool catalog name sets) is invariant for this scheduler's
        # lifetime — only the topology context swaps per request
        self._verifier = None

    _FP_CACHE_CAP = 4
    _BATCH_CACHE_CAP = 4
    # entry-count bound on the per-class row cache: each row carries two
    # [K,V] bool planes plus small vectors (~10-20KB at production K/V),
    # so 20k entries stays in the low hundreds of MB — far above any real
    # class-mix working set (the diverse 50k bench lands ~6k classes) but
    # safely below sidecar OOM territory under label-churn signatures
    _ROW_CACHE_CAP = 20_000

    def update_topology_context(self, topology: Optional[Topology]) -> None:
        """Swap the cluster topology context in place. Per-round Topology
        state is rebuilt from the context on every solve, so a cached
        scheduler (solverd reuses them across RPC calls keyed on the
        problem fingerprint, which deliberately ignores the pod-derived
        excluded-uid list) takes the request's live context here instead
        of rebuilding the whole scheduler."""
        self._topology_context = topology

    def _dev(self, a: np.ndarray):
        """Host->device put with byte accounting for the phase breakdown.
        Multi-device schedulers commit the copy replicated across the mesh
        (every device pays the full bytes)."""
        self._h2d_bytes += a.nbytes
        self._h2d_dev_bytes += a.nbytes
        if self._mesh is None:
            return jnp.asarray(a)
        return jax.device_put(a, self._repl)

    def _dev_slots(self, a: np.ndarray, dim: int = 0):
        """Host->device put for slot-axis arrays: lands PRE-SHARDED over
        the mesh, so the fingerprint-keyed prepared-state caches hold
        sharded device copies and a steady-state re-solve stays
        hit-for-hit with zero re-placement. Per-device h2d bytes scale
        1/devices for these planes — the whole point of the slot mesh.
        graftlint GL501 resolves SlotState placement through this helper
        interprocedurally (and GL503 flags host gathers of what it
        placed), so state that bypasses it fails the lint at edit time."""
        self._h2d_bytes += a.nbytes
        if self._mesh is None:
            self._h2d_dev_bytes += a.nbytes
            return jnp.asarray(a)
        self._h2d_dev_bytes += -(-a.nbytes // self.devices)
        return jax.device_put(
            a, pmesh.axis_sharding(self._mesh, a.ndim, dim)
        )

    # ------------------------------------------------------------------

    def prewarm(self, class_buckets: Sequence[int] = (8, 64, 256)) -> None:
        """Compile (or load from the persistent compile cache) the FFD
        kernels for the common class-count buckets before the first real
        batch. Kernel shapes bucket on the class axis (_bucket), so a
        synthetic solve with N distinct pod shapes warms the same jit entry
        a real N-class batch hits; on a restarted operator with the on-disk
        XLA cache (utils/jaxenv.enable_persistent_compile_cache) this turns
        the first-batch compile cliff into a cache load (VERDICT r4 item 4).
        The jit cache is process-global — any DeviceScheduler instance
        warms every later one with the same catalog/pool shapes."""
        GIB = 2.0**30
        from karpenter_core_tpu.api.objects import ObjectMeta

        for target in class_buckets:
            pods = [
                Pod(
                    metadata=ObjectMeta(name=f"prewarm-{target}-{i}"),
                    resource_requests={
                        "cpu": 0.001 * (1 + i % 64),
                        "memory": 0.125 * GIB * (1 + i // 64),
                    },
                )
                for i in range(target)
            ]
            self.solve(pods)

    def solve(self, pods: List[Pod]) -> Results:
        """Device solve + host decode + relaxation outer loop.

        Each relaxation round re-solves the FULL pod set (relaxations mutate
        only previously-failed pods' specs), so placements from earlier rounds
        are never dropped — the same world-re-solve the reference reaches via
        requeue-on-relax (scheduler.go:251-258).

        Implemented as a driven generator (_solve_gen): the generator runs
        every host phase and YIELDS at each kernel dispatch, so the solo
        path here and the cross-problem batch driver (solve_batch) execute
        the identical per-problem pipeline — only the kernel runner
        differs (direct dispatch vs a vmapped multi-problem batch)."""
        return _drive_solo(self._solve_gen(pods))

    def _solve_gen(self, pods: List[Pod]):
        all_pods = list(pods)
        # refreshed by _sorted_classes each round; False covers the
        # degenerate no-template/no-existing early return, where nothing
        # places and the gang backstop has nothing to strip
        self._gangsched_engaged = False
        errors: Dict[str, str] = {}
        claims: List[InFlightNodeClaim] = []
        # fresh per-solve copy: place_pod subtracts from it as fallback
        # claims open, and a reused scheduler must not accumulate rounds
        self._round_remaining = {
            k: dict(v) for k, v in self.remaining_resources.items()
        }
        existing_sims: List[ExistingNodeSim] = []
        E = len(self.existing_nodes)
        base_slots = self.max_slots
        while base_slots < E:
            base_slots *= 2
        # Adaptive slot axis: every kernel plane is [N, ...], so running a
        # 235-node solve at the caller's 4096-slot ceiling wastes ~16x the
        # per-step HBM traffic on slots that can never take. Warm solves
        # start at a bucket sized from the last solve's observed usage
        # (2x headroom); an overflow costs one cheap small-N scan and
        # retries larger, so the packing is identical — padding slots are
        # inert by construction (kind=0 never takes; tested by the
        # slot-axis-invariance parity test).
        if self._slots_hint:
            max_slots = min(
                base_slots,
                max(_bucket(max(2 * self._slots_hint, E + 1)), 64),
            )
        else:
            max_slots = base_slots
        self._round_frozen = None  # vocab union seed is per solve() call
        # anytime clock: every relax-budget check measures from the
        # moment THIS solve started, so "budget expired" always leaves
        # the already-computed FFD answer as the serve
        self._solve_t0 = time.perf_counter()
        self.last_phase_stats = stats = {
            "plan_s": 0.0, "prepare_s": 0.0, "kernel_s": 0.0,
            "decode_s": 0.0, "fetch_bytes": 0, "h2d_bytes": 0,
            "rounds": 0, "slots": max_slots, "used_slots": 0,
            "prep_cache_hits": 0, "prep_cache_misses": 0,
            # multi-device accounting: per-device h2d/fetch bytes (sharded
            # planes divide across the mesh, replicated ones don't), so
            # single- vs multi-device runs compare like for like
            "n_devices": self.devices,
            "h2d_dev_bytes": 0, "fetch_dev_bytes": 0,
            # which backend served this solve (bench/ops attribution)
            "solver_mode": self.solver_mode,
            # ... and which kernel backend answered its scan dispatches
            "kernel_backend": self.kernel_backend,
        }
        if self.solver_mode == "relax":
            stats["relax"] = {}

        from karpenter_core_tpu.metrics import wiring as m

        # relaxation terminates naturally: each relax() strips one soft term
        # (preferences.go:38-57); the greedy oracle loops the same way
        first_round = True
        while True:
            if not first_round:
                m.SOLVER_RELAX_ROUNDS.inc()
            first_round = False
            stats["rounds"] += 1
            stats["slots"] = max_slots
            # per-round solve duration = this round's OWN phase work
            # (plan/prepare/kernel/decode deltas), not wall across the
            # yield — under solve_batch the generator suspends at the
            # dispatch while batch-mates run, and a wall timer would
            # charge their work to this problem's histogram
            r0 = {
                k: stats[k]
                for k in ("plan_s", "prepare_s", "kernel_s", "decode_s")
            }
            result = yield from self._solve_once_gen(all_pods, max_slots)
            m.SOLVER_SOLVE_DURATION.observe(
                sum(stats[k] - r0[k] for k in r0)
            )
            if result is None:  # slot overflow — retry larger
                if max_slots >= _SLOT_HARD_CAP:
                    errors = {
                        p.uid: f"solver slot overflow at {max_slots} slots"
                        for p in all_pods
                    }
                    return Results(
                        new_node_claims=[], existing_nodes=[], pod_errors=errors
                    )
                if max_slots < base_slots:
                    # the adaptive shrink guessed low — jump back toward
                    # the configured ceiling fast (x4) before the classic
                    # doubling takes over past it
                    max_slots = min(max_slots * 4, base_slots)
                else:
                    max_slots *= 2
                continue
            claims, existing_sims, failed, evictions = result
            errors = {p.uid: msg for p, msg in failed}
            if not failed:
                break
            relaxed_any = False
            for p, _msg in failed:
                if self.preferences.relax(p):
                    relaxed_any = True
            if not relaxed_any:
                break
        if stats["used_slots"]:
            # decay, don't snap: a burst of small solves (prewarm, quiet
            # cluster) must not drop the hint so far a normal batch pays a
            # ladder of overflow retries
            prev = self._slots_hint or 0
            self._slots_hint = max(int(stats["used_slots"]), prev // 2)

        for c in claims:
            c.finalize_scheduling()
        results = Results(
            new_node_claims=claims,
            existing_nodes=existing_sims,
            pod_errors=errors,
            evictions=evictions,
        )
        if self._gangsched_engaged:
            # the decode-seam atomicity backstop (the kernel already rolled
            # failed gangs back on device; this catches host-repair
            # divergence) — it MUST run before verification, which treats a
            # partially materialized gang as a hard violation
            gangmod.enforce_atomicity(results, all_pods)
            # topoaware backstops (ISSUE 20), same seam and same ordering
            # contract: distance stripping before eviction pruning (a
            # stripped gang's evictions must prune with it) and before
            # verification, which re-derives the bound independently and
            # treats an exceeded hard max-hops as a hard violation
            node_labels = {
                n.name: getattr(n, "labels", None) or {}
                for n in self.existing_nodes
            }
            gangmod.enforce_distance(results, all_pods, node_labels)
            gangmod.prune_evictions(results)
            # rank-ordered slot assignment runs LAST: a pure within-class
            # permutation of an already-final packing (rank-adjacent pods
            # land network-adjacent; the verifier checks adjacency)
            gangmod.rank_order_pods(results, all_pods, node_labels)
            whole = sum(
                1
                for mpods in gangmod.gang_members(all_pods).values()
                if mpods and all(p.uid in results.pod_errors for p in mpods)
            )
            if whole:
                m.SOLVER_GANG_UNSCHEDULABLE.inc(by=whole)
        if self.verify:
            from karpenter_core_tpu.solver import verify as verifymod

            t0 = time.perf_counter()
            if self._verifier is None:
                self._verifier = verifymod.ResultVerifier(
                    self.nodepools,
                    self.instance_types,
                    existing_nodes=self.existing_nodes,
                    daemonset_pods=self.daemonset_pods,
                    topology=self._topology_context,
                    unavailable_offerings=self.unavailable_offerings,
                )
            else:
                # a cached scheduler (solverd reuse) swaps contexts per
                # request; everything else the verifier holds is invariant
                self._verifier.topology = self._topology_context
            violations = self._verifier.verify(results, all_pods)
            stats["verify_s"] = time.perf_counter() - t0
            if violations:
                verifymod.reject(violations, "inproc", self.recorder)
                return self._verified_fallback(all_pods)
        return results

    def _verified_fallback(self, pods: List[Pod]) -> Results:
        """A device result failed verification: re-solve on the host
        greedy path over the same inputs (the RemoteScheduler degradation
        twin, one layer down). Correctness beats speed exactly once — the
        rejection metric says the device tier needs attention. Problems
        carrying priorities/gangs degrade through the tiered-greedy-with-
        preemption wrapper (solver/gangs.host_gang_solve), so degraded
        means slower, never semantically different."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
            Scheduler,
        )

        def make_scheduler():
            return Scheduler(
                self.nodepools,
                self.instance_types,
                existing_nodes=self.existing_nodes,
                daemonset_pods=self.daemonset_pods,
                topology=self._topology_context,
                unavailable_offerings=self.unavailable_offerings,
            )

        return gangmod.degraded_solve(
            make_scheduler, pods, self.existing_nodes
        )

    # ------------------------------------------------------------------

    def _solve_once_gen(self, pods: List[Pod], max_slots: int):
        """One solve round as a generator: host prepare, then a single
        ``yield`` of a _KernelRequest at the device dispatch (the driver
        sends back (state, takes_bc, unplaced_bc)), then fetch + decode.
        Returns None on slot overflow (caller retries larger)."""
        if not self.templates and not self.existing_nodes:
            # no viable templates and no existing capacity: everything fails
            return [], [], [(p, "no nodepool matched pod") for p in pods], {}

        stats = self.last_phase_stats
        self._h2d_bytes = 0
        self._h2d_dev_bytes = 0
        t0 = time.perf_counter()
        # one Topology per solve round; every pod's groups are (re)built so
        # relaxed specs take effect (topology.go NewTopology:60-86)
        ctx = self._topology_context
        topo = Topology(
            domains={
                k: set(v)
                for k, v in (
                    ctx.domains if ctx is not None else self.domains_universe
                ).items()
            },
            existing_pods=ctx.existing_pods if ctx is not None else None,
            excluded_pod_uids=ctx.excluded_pods if ctx is not None else (),
        )
        topo.ensure_inverse_initialized()
        for p in pods:
            # constraint-free pods build no groups; skipping the call is the
            # 50k-path win (update() itself is a no-op for them)
            if p.topology_spread_constraints or p.affinity is not None:
                topo.update(p)

        # the topology planner decides which constraint shapes run in-kernel
        # (device count state) and which fall back to the host algebra
        classes = self._sorted_classes(pods, topo)
        plan = topoplan.plan_topology(classes, topo)
        self._composition_cache: Dict[tuple, tuple] = {}
        stats["plan_s"] += time.perf_counter() - t0

        from karpenter_core_tpu.metrics import wiring as m

        t0 = time.perf_counter()
        try:
            with m.SOLVER_PREPARE_DURATION.time():
                prep = self._prepare_with_vocab(plan, max_slots, topo)
                steps = self._class_steps(prep)
        except _SlotOverflow:
            return None
        stats["prepare_s"] += time.perf_counter() - t0
        stats["h2d_bytes"] += self._h2d_bytes
        stats["h2d_dev_bytes"] += self._h2d_dev_bytes

        # relaxsolve (ISSUE 13): a cached WON verdict for this exact
        # class batch applies the rounded template override to the ONE
        # dispatch below — warm relax solves cost a single scan, exactly
        # like ffd mode, and pack the relaxation's better answer. An
        # unevaluated batch dispatches plain first (the anytime answer)
        # and _relax_improve runs the optimizer after.
        relax_verdict = None
        if self.solver_mode == "relax":
            relax_verdict = prep._batch.get("relax_verdict")
            if relax_verdict is not None and relax_verdict.get("won"):
                steps = self._override_steps(
                    prep, steps,
                    relax_verdict["new_template"], relax_verdict["kstar"],
                )

        # the device dispatch is the generator's yield point: the solo
        # driver answers with ffd_solve_donated + aggregate_takes, the
        # batch driver stacks compatible requests and answers from one
        # vmapped dispatch — the rest of the round is identical. The
        # donating solo twin consumes init_state's buffers in place (HBM
        # churn); _Prepared rebuilds them per round, so mark them spent.
        # The driver reports this problem's kernel-dispatch share (a
        # timer held open across the yield would bill batch-mates' work
        # to this problem's histogram); the fetches below are ours.
        state, takes_bc, unplaced_bc, kernel_share_s = yield _KernelRequest(
            init_state=prep.init_state,
            steps=steps,
            statics=prep.statics,
            level_iters=prep.level_iters,
            step_class=prep.step_class,
            num_classes=prep.n_classes_padded,
            devices=self.devices,
            n_slots=prep.n_slots,
            # gang-atomic kernels only when kernel-enforced gangs exist;
            # None keeps the exact pre-gang jit entries (byte parity)
            gang_of_step=(
                prep.step_gang if prep.gang_min is not None else None
            ),
            gang_min=prep.gang_min,
            mode=self.solver_mode,
            backend=self.kernel_backend,
        )
        prep.init_state = None
        t0 = time.perf_counter()
        # the per-step takes were fused down to per-class decision planes
        # on device by the driver; fetch the tiny head scalars to learn how
        # many slots the solve actually touched — every remaining plane is
        # sliced to that bucketed window before the single bulk fetch, so
        # the device->host transfer scales with nodes PACKED, not max_slots
        head = jax.device_get(
            {"overflow": state.overflow, "next_free": state.next_free}
        )
        if bool(head["overflow"]):
            kdt = kernel_share_s + (time.perf_counter() - t0)
            m.SOLVER_KERNEL_DURATION.observe(kdt)
            stats["kernel_s"] += kdt
            return None

        # -- relaxsolve improve pass (ISSUE 13) ----------------------------
        # With the baseline (anytime) answer in hand, run the convex-
        # relaxation optimizer and adopt its packing only when the scored
        # comparison says it strictly wins; the preemption pass and decode
        # below then operate on the winner, so tiers/gangs/evictions are
        # relaxation-composed, not special-cased.
        if self.solver_mode == "relax":
            if relax_verdict is not None:
                rstats = stats.get("relax")
                if rstats is not None:
                    rstats["outcome"] = (
                        "cached_won"
                        if relax_verdict.get("won")
                        else "cached_kept_ffd"
                    )
                    rstats["cached"] = True
                m.SOLVER_RELAX_BACKEND.inc({"outcome": "cached"})
            else:
                state, takes_bc, unplaced_bc, rdt = yield from (
                    self._relax_improve(
                        prep, steps, state, takes_bc, unplaced_bc
                    )
                )
                kernel_share_s += rdt
                # the adopted packing may differ from the baseline whose
                # head was fetched above: the used-slot fetch window (and
                # the adaptive slot hint) must follow the WINNER's state
                head = jax.device_get(
                    {"overflow": state.overflow, "next_free": state.next_free}
                )

        # -- preemption pass (gangsched, ISSUE 10) -------------------------
        # Still-unplaced positive-tier gang-free classes get one more
        # device dispatch against the evictable-capacity planes; the
        # selected eviction set comes back as claims and the freed
        # capacity inflates the victims' sims so decode accepts the
        # preempted placements (drain-before-bind makes it real).
        evictions: Dict[str, List[str]] = {}
        C = len(prep.classes)
        if prep.ev is not None and prep.step_tier is not None and C:
            u_host = np.asarray(jax.device_get(unplaced_bc))[:C]
            goc = prep._batch["gang_of_class"][:C]
            toc = prep._batch["tier_of_class"][:C]
            if bool(
                ((u_host > 0) & (toc > 0) & (goc == gangmod.GANG_FREE)).any()
            ):
                J = len(plan.steps)
                Jp = int(prep.step_class.shape[0])
                u_step = jnp.where(
                    jnp.arange(Jp) < J,
                    unplaced_bc[prep.step_class], 0
                ).astype(jnp.int32)
                extra_bc, mleft_bc, evicted, pdt = yield _KernelRequest(
                    init_state=state,
                    steps=steps,
                    statics=prep.statics,
                    level_iters=prep.level_iters,
                    step_class=prep.step_class,
                    num_classes=prep.n_classes_padded,
                    devices=self.devices,
                    n_slots=prep.n_slots,
                    kind="preempt",
                    step_tier=prep.step_tier,
                    step_gang=prep.step_gang,
                    unplaced=u_step,
                    ev=prep.ev,
                    backend=self.kernel_backend,
                )
                kernel_share_s += pdt
                takes_bc = takes_bc + extra_bc
                unplaced_bc = mleft_bc
                ev_host = np.asarray(jax.device_get(evicted))
                for ei, uids in enumerate(prep.ev_uids):
                    hits = np.nonzero(ev_host[ei, : len(uids)])[0]
                    if not len(hits):
                        continue
                    sim = prep.existing_sims[ei]
                    evictions[sim.name] = [uids[j] for j in hits]
                    freed = resutil.merge(
                        *(prep.ev_freed[ei][j] for j in hits)
                    )
                    # the victims' capacity is credited to the sim so the
                    # decode adds (and only they) see it; the operator
                    # drains the victims before binding
                    sim.cached_available = resutil.merge(
                        sim.cached_available, freed
                    )

        N = prep.n_slots
        used = max(int(head["next_free"]), len(prep.existing_sims), 1)
        stats["used_slots"] = max(stats["used_slots"], used)
        ub = min(N, _bucket(used))

        def win(a):  # bucketed used-slot window on the slot axis
            return a[:ub] if ub < N else a

        fetch = dict(
            takes_bc=takes_bc[:, :ub] if ub < N else takes_bc,
            unplaced_bc=unplaced_bc,
            template=win(state.template),
        )
        if plan.has_device_topology():
            fetch.update(
                valmask=win(state.valmask),
                defines=win(state.defines),
                complement=win(state.complement),
                gt=win(state.gt),
                lt=win(state.lt),
                itmask=win(state.itmask),
                hcount=win(state.hcount),
                zcount=state.zcount,
            )
        else:
            # only the topology-free decode reads class_it host-side
            # (_decode_composition); it rides the single post-scan fetch
            fetch["class_it"] = prep.class_it
        # per-device fetch share BEFORE the gather: a slot-sharded plane
        # costs each device ~1/devices of its bytes, a replicated one the
        # full bytes (`.nbytes`/`.sharding` are metadata — no transfer)
        fetched_dev = 16
        for v in fetch.values():
            n = int(getattr(v, "nbytes", 0))
            sh = getattr(v, "sharding", None)
            if sh is not None and not sh.is_fully_replicated:
                n = -(-n // self.devices)
            fetched_dev += n
        out = jax.device_get(fetch)
        kdt = kernel_share_s + (time.perf_counter() - t0)
        m.SOLVER_KERNEL_DURATION.observe(kdt)
        stats["kernel_s"] += kdt
        fetched = sum(np.asarray(v).nbytes for v in out.values()) + 16
        stats["fetch_bytes"] += fetched  # + the head scalars
        stats["fetch_dev_bytes"] += fetched_dev
        m.SOLVER_FETCH_BYTES.inc(by=fetched)
        # slice bucketed device shapes back to the natural sizes decode
        # (and the topoplan arrays) index with
        C = len(prep.classes)
        sh = self._pad_shapes
        out["takes_bc"] = np.asarray(out["takes_bc"])[:C]
        out["unplaced_bc"] = np.asarray(out["unplaced_bc"])[:C]
        if plan.has_device_topology():
            out["valmask"] = np.asarray(out["valmask"])[:, : sh["K"], : sh["V"]]
            out["defines"] = np.asarray(out["defines"])[:, : sh["K"]]
            out["complement"] = np.asarray(out["complement"])[:, : sh["K"]]
            out["gt"] = np.asarray(out["gt"])[:, : sh["K"]]
            out["lt"] = np.asarray(out["lt"])[:, : sh["K"]]
            out["itmask"] = np.asarray(out["itmask"])[:, : sh["T"]]
            out["hcount"] = np.asarray(out["hcount"])[:, : sh["Gh"]]
            out["zcount"] = np.asarray(out["zcount"])[: sh["Gz"], : sh["V"]]
        else:
            prep.class_it = np.asarray(out["class_it"])[:, : sh["T"]]
        t0 = time.perf_counter()
        with m.SOLVER_DECODE_DURATION.time():
            claims, existing_sims, failed = self._decode(prep, out)
        stats["decode_s"] += time.perf_counter() - t0

        # ineligible topology classes: host loop over the post-device cluster
        t0 = time.perf_counter()
        fallback_pods = [p for cls in plan.fallback_classes for p in cls.pods]
        if fallback_pods:
            m.SOLVER_HOST_FALLBACK_PODS.inc(
                {"cause": "ineligible"}, by=len(fallback_pods)
            )
        fallback_requests = {
            p.uid: resutil.requests_for_pods(p) for p in fallback_pods
        }
        for p in by_cpu_and_memory_descending(fallback_pods, fallback_requests):
            err = self._host_fallback_add(
                p, claims, existing_sims, topo, fallback_requests[p.uid]
            )
            if err is not None:
                failed.append((p, err))
        stats["decode_s"] += time.perf_counter() - t0
        return claims, existing_sims, failed, evictions

    # -- relaxsolve (ISSUE 13) -----------------------------------------

    def _override_steps(self, prep: _Prepared, steps: ClassStep,
                        nt, ks) -> ClassStep:
        """Lift a per-class (new_template, kstar) override onto the
        scanned step axis: gather by the step->class index, keep pad
        steps inert. A cheap local copy — the cached ClassStep on
        prep._batch is never mutated."""
        Jp = int(prep.step_class.shape[0])
        J = len(prep.plan.steps)
        valid = jnp.arange(Jp) < J
        return steps._replace(
            new_template=jnp.where(valid, nt[prep.step_class], -1),
            kstar=jnp.where(valid, ks[prep.step_class], 0),
        )

    def _relax_expired(self) -> bool:
        return (
            self.relax_budget_s is not None
            and time.perf_counter() - self._solve_t0 > self.relax_budget_s
        )

    def _relax_improve(self, prep: _Prepared, steps: ClassStep,
                       state, takes_bc, unplaced_bc):
        """The relax backend's optimizing pass, as a generator riding the
        same kernel-dispatch seam as the solve itself.

        The caller holds the finished plain-FFD dispatch — the ANYTIME
        answer. This pass (1) checks the wall budget (expired -> serve
        FFD), (2) dispatches the projected-gradient assignment + rounding
        (ops/relax.relax_choose; a no-change rounding short-circuits),
        (3) re-runs the unmodified FFD/gang scan from a fresh init state
        with the rounded (new_template, kstar) override, and (4) adopts
        the candidate only when the on-device score (unplaced, fresh
        nodes, $-cost proxy) strictly improves — rounding that loses
        falls back to the FFD result. The verdict caches on the class
        batch, so warm re-solves of the same problem dispatch ONCE with
        the winning override (p50 parity with ffd mode) until the
        fingerprint/plan/class mix changes.

        Returns (state, takes_bc, unplaced_bc, kernel_seconds) — the
        winner's."""
        from karpenter_core_tpu.metrics import wiring as m

        rstats = self.last_phase_stats.setdefault("relax", {})
        extra = 0.0

        def outcome(tag: str):
            rstats["outcome"] = tag
            m.SOLVER_RELAX_BACKEND.inc({"outcome": tag})

        planes = prep._batch.get("relax")
        if planes is None:
            # no fresh-node axis (catalog/template-free problem): nothing
            # to optimize, the FFD answer is the answer
            outcome("infeasible")
            return state, takes_bc, unplaced_bc, extra
        if self._relax_expired():
            outcome("deadline")
            return state, takes_bc, unplaced_bc, extra
        # incsolve warm start (ISSUE 16): lower the ledger's prior
        # per-class template choice ({signature -> nodepool name}, set by
        # solver/incremental) to a [Cp] index vector over THIS prep's
        # template axis; -1 (cold) everywhere the ledger is silent or the
        # pool no longer templates, so a ledger-less solve dispatches the
        # bit-identical cold kernel.
        Cp = int(prep.new_template.shape[0])
        wvec = np.full((Cp,), -1, dtype=np.int32)
        if self._relax_warm:
            pool_to_tmpl = {
                t.nodepool_name: si for si, t in enumerate(self.templates)
            }
            for ci, cls in enumerate(prep.classes[:Cp]):
                si = pool_to_tmpl.get(self._relax_warm.get(cls.signature))
                if si is not None:
                    wvec[ci] = si
            rstats["warm_classes"] = int((wvec >= 0).sum())
        # topoaware soft term (ISSUE 20): the per-(gang class, template)
        # hop-distance plane rides as a trailing optional; absent (None)
        # for label-free problems the tuple is one leaf shorter, so the
        # shape key never buckets topo and non-topo relax dispatches
        # together and the non-topo trace stays byte-identical
        relax_tuple = (
            planes["viable"], planes["k_cs"], planes["k_node"],
            planes["podcost"], planes["counts"], planes["gang_id"],
            prep.new_template, prep.kstar,
            jnp.asarray(wvec),
        )
        topo_np = prep._batch.get("topo_cost_of_class")
        if topo_np is not None:
            tc_d = prep._batch.get("topo_cost_d")
            if tc_d is None:
                Cp = int(prep.new_template.shape[0])
                Sp = int(prep.tmpl_price_d.shape[0])
                tc_d = self._dev(
                    _pad(topo_np, {0: Cp, 1: Sp}, 0.0)
                )
                prep._batch["topo_cost_d"] = tc_d
            relax_tuple = relax_tuple + (tc_d,)
        nt, ks, changed, dt = yield _KernelRequest(
            init_state=None, steps=None, statics=None,
            level_iters=prep.level_iters, step_class=None,
            num_classes=prep.n_classes_padded, devices=self.devices,
            n_slots=prep.n_slots, kind="relax", mode="relax",
            relax=relax_tuple,
            relax_iters=self.relax_iters, relax_gangs=planes["n_gangs"],
            backend=self.kernel_backend,
        )
        extra += dt
        rstats["template_moves"] = int(changed)
        if int(changed) == 0:
            # rounding agrees with first-template-wins: the FFD packing
            # IS the relaxation's packing; remember so warm solves skip
            # even the assignment dispatch
            prep._batch["relax_verdict"] = {"won": False}
            outcome("noop")
            return state, takes_bc, unplaced_bc, extra
        if self._relax_expired():
            outcome("deadline")
            return state, takes_bc, unplaced_bc, extra
        # candidate: the byte-identical scan (gang twin included) from a
        # fresh init state with the rounded override riding ClassStep
        init2 = self._make_init_state(*prep.init_args)
        steps2 = self._override_steps(prep, steps, nt, ks)
        state2, takes2_bc, unplaced2_bc, dt2 = yield _KernelRequest(
            init_state=init2, steps=steps2, statics=prep.statics,
            level_iters=prep.level_iters, step_class=prep.step_class,
            num_classes=prep.n_classes_padded, devices=self.devices,
            n_slots=prep.n_slots,
            gang_of_step=(
                prep.step_gang if prep.gang_min is not None else None
            ),
            gang_min=prep.gang_min,
            mode="relax",
            backend=self.kernel_backend,
        )
        extra += dt2
        t0 = time.perf_counter()
        if bool(jax.device_get(state2.overflow)):
            # the override needed more slots than the baseline's axis —
            # keep the FFD packing rather than re-growing for a candidate
            prep._batch["relax_verdict"] = {"won": False}
            outcome("overflow")
            extra += time.perf_counter() - t0
            return state, takes_bc, unplaced_bc, extra
        score_f = relax_ops.relax_score(
            state, prep.tmpl_price_d, unplaced_bc
        )
        score_r = relax_ops.relax_score(
            state2, prep.tmpl_price_d, unplaced2_bc
        )
        sf = jax.device_get(score_f)
        sr = jax.device_get(score_r)
        extra += time.perf_counter() - t0
        key_f = (int(sf[0]), int(sf[1]), float(sf[2]))
        key_r = (int(sr[0]), int(sr[1]), float(sr[2]))
        rstats.update(
            unplaced_ffd=key_f[0], nodes_ffd=key_f[1],
            cost_ffd=round(key_f[2], 3),
            unplaced_relax=key_r[0], nodes_relax=key_r[1],
            cost_relax=round(key_r[2], 3),
        )
        if key_r < key_f:
            prep._batch["relax_verdict"] = {
                "won": True, "new_template": nt, "kstar": ks,
            }
            outcome("won")
            return state2, takes2_bc, unplaced2_bc, extra
        prep._batch["relax_verdict"] = {"won": False}
        outcome("lost")
        return state, takes_bc, unplaced_bc, extra

    # ------------------------------------------------------------------

    def _sorted_classes(self, pods: List[Pod], topo: Topology) -> List[PodClass]:
        # labels/pod-affinity join the class key only when a topology group
        # could observe them (see _spec_signature)
        label_aware = bool(topo.topologies or topo.inverse_topologies)
        classes = group_pods(pods, label_aware=label_aware)
        # class order = pod queue order lifted to classes (queue.go:76-112)
        classes.sort(
            key=lambda c: (
                -c.requests.get("cpu", 0.0),
                -c.requests.get("memory", 0.0),
                min(p.metadata.creation_timestamp for p in c.pods),
            )
        )
        if label_aware:
            # Host-floor-first ordering — a deliberate, measured improvement
            # over the reference's pure size order (queue.go:76-112).
            # Hostname-keyed anti-affinity/spread classes need DISTINCT
            # hosts (min floats at zero while fresh nodes are creatable,
            # topologygroup.go:235-238): the slot floor they force is
            # max(per-group demand), independent of WHEN they run — but run
            # mid-scan (size order), early such classes find few existing
            # slots and open fresh ones the oracle's pod-interleaved walk
            # avoids. Running them FIRST establishes the host floor with
            # the minimum slot count, and the capacity-driven classes then
            # fill those slots instead of opening their own: the diverse
            # 5k topology mix drops 127 -> 91 nodes (greedy oracle: 121),
            # the 50k mix 314 -> 235 (greedy: 315). Stable within ranks,
            # so size order is preserved among peers.
            # Promote ONLY classes whose owned groups are exclusively
            # hostname anti-affinity/spread: a promoted class must not
            # depend on other classes' placements. A class that also owns a
            # pod-AFFINITY group (or any label-keyed group) placed ahead of
            # its target would find zero count>0 domains and fail pods the
            # size order places.
            def rank(cls: PodClass) -> int:
                owned = topo._owned.get(cls.pods[0].uid, ())
                if not owned:
                    return 2
                best = 2
                for g in owned:
                    if g.key != apilabels.LABEL_HOSTNAME:
                        return 2
                    if g.type == TYPE_ANTI_AFFINITY:
                        best = min(best, 0)
                    elif g.type == TYPE_SPREAD:
                        best = min(best, 1)
                    else:  # hostname-keyed affinity still depends on targets
                        return 2
                return best

            classes.sort(key=rank)
        # O(classes) gangsched gate, stashed so the per-solve result
        # post-processing (_solve_gen) doesn't re-derive it with an
        # O(pods) annotation rescan at 50k pods
        self._gangsched_engaged = any(
            c.tier != 0 or c.gang is not None for c in classes
        )
        if self._gangsched_engaged:
            # gangsched (ISSUE 10): priority tier is the PRIMARY order —
            # the scan claims capacity in class order, so tier-descending
            # is what makes "a lower tier can never starve a higher one"
            # true by construction. Within a tier, gang members pull
            # adjacent (anchored at the gang's first member) so the
            # co-location state their joint masks narrow is warm when the
            # next member scans. The sort is stable, so plain problems
            # never enter this branch and keep the exact pre-gang order
            # (byte parity). Shares solver/gangs.gang_adjacent_order with
            # the host fallback's pod sort — one ordering, two layers.
            classes = gangmod.gang_adjacent_order(
                classes,
                lambda c: c.tier,
                lambda c: None if c.gang is None else c.gang[0],
            )
        return classes

    def _prepare(
        self, pods: List[Pod], max_slots: int, topo: Topology
    ) -> _Prepared:
        """Topology-free prepare entry for the consolidation sweep and the
        sharded-solver tests (callers guarantee no topology-coupled pods)."""
        # direct prepares are not relaxation rounds: don't union a previous
        # solve()'s vocab into this closed world
        self._round_frozen = None
        plan = topoplan.plan_topology(self._sorted_classes(pods, topo), topo)
        return self._prepare_with_vocab(plan, max_slots, topo)

    # -- prepared-state construction (cached; see __init__) ---------------

    def _exist_reqs(self) -> List[Requirements]:
        if self._exist_label_reqs is None:
            self._exist_label_reqs = [
                Requirements.from_labels(n.labels) for n in self.existing_nodes
            ]
        return self._exist_label_reqs

    def _vocab_universe(self):
        """Scheduler-lifetime label universe: (base key->values from
        templates + existing-node labels + offerings, IT-requirement
        key->values kept separate — catalog instance types contribute
        VALUES only for keys some other entity mentions; see the
        closed-world argument in solver/vocab.py and the exactness note on
        the original inline build)."""
        if self._universe is None:
            base: Dict[str, set] = {}

            def obs(reqs):
                # graftlint: disable=GL201 -- pure set-union accumulation;
                # the interning below (_build_vocab) sorts before minting ids
                for key, req in reqs.items():
                    base.setdefault(key, set()).update(req.values)

            for t in self.templates:
                obs(t.requirements)
            for r in self._exist_reqs():
                obs(r)
            for it in self._catalog_union():
                for off in it.offerings:
                    obs(off.requirements)
            it_vals: Dict[str, set] = {}
            for it in self._catalog_union():
                # graftlint: disable=GL201 -- pure set-union accumulation;
                # _build_vocab sorts before minting ids
                for key, req in it.requirements.items():
                    it_vals.setdefault(key, set()).update(req.values)
            self._universe = (base, it_vals)
        return self._universe

    def _build_vocab(self, classes: List[PodClass], plan: topoplan.TopoPlan):
        """Canonical closed-world vocab for one solve round.

        Keys and values intern in SORTED order, so two rounds with the
        same label universe produce identical id assignments — the
        fingerprint equality the prepared-state caches key on. Relaxation
        rounds union the previous round's vocab (_round_frozen): a relax
        only strips preferred terms, so the union IS the round-1 vocab and
        every cached tensor survives the re-solve."""
        from karpenter_core_tpu.solver.vocab import Vocab

        base, it_vals = self._vocab_universe()
        # graftlint: disable=GL201 -- all three loops below are pure
        # set-union accumulation into `merged`; the interning loop at the
        # bottom sorts keys AND values before minting any id, so iteration
        # order here cannot reach the fingerprint
        merged = {k: set(v) for k, v in base.items()}
        for cls in classes:
            for key, req in cls.requirements.items():  # graftlint: disable=GL201 -- set union, id-free
                merged.setdefault(key, set()).update(req.values)
        # catalog ITs contribute values only for keys mentioned by a
        # non-catalog entity (class/template/node/offering)
        mentioned = set(merged)
        for key, vals in it_vals.items():  # graftlint: disable=GL201 -- set union, id-free
            tgt = merged.setdefault(key, set())
            if key in mentioned:
                tgt.update(vals)
        # topology-domain universe joins the closed world (the kernel's
        # admissibility masks index the label-group keys' value rows)
        for dg in plan.label_groups:
            merged.setdefault(dg.key, set()).update(dg.group.domains)
        if self._round_frozen is not None:
            for key, names in zip(
                self._round_frozen.key_names, self._round_frozen.value_names
            ):
                merged.setdefault(key, set()).update(names)
        v = Vocab()
        for key in sorted(merged):
            v.key_id(key)
            for val in sorted(merged[key]):
                v.value_id(key, val)
        return v.finalize()

    def _resource_axis(self, classes: List[PodClass]) -> List[str]:
        """Resource axis: the 4 well-known names, then the catalog/daemon
        extras, then any class-only extras — each block sorted so the axis
        (and with it the fingerprint) is stable under drifting pod mixes.
        Daemon overhead joins every fresh claim's requests, so its resource
        names must be on the axis or the vectorized fit check would
        silently drop them."""
        if self._base_resources is None:
            names = dict.fromkeys(["cpu", "memory", "pods", "ephemeral-storage"])
            extra = set()
            for it in self._catalog_union():
                extra.update(it.allocatable())
            for o in self.daemon_overhead:
                extra.update(o)
            for n in sorted(extra):
                if n not in names:
                    names[n] = None
            self._base_resources = list(names)
        names = dict.fromkeys(self._base_resources)
        extra = set()
        for c in classes:
            extra.update(c.requests)
        for n in sorted(extra):
            if n not in names:
                names[n] = None
        return list(names)

    def _stat_inc(self, key: str) -> None:
        st = self.last_phase_stats
        if key in st:
            st[key] += 1

    def _fp_entry(self, frozen, resource_names: List[str]) -> Tuple[dict, int]:
        """Catalog/template/existing-node tensors for one closed world,
        cached per (vocab fingerprint, resource axis, existing-node set).
        Nothing here depends on the pod mix: steady-state solves and every
        relaxation round reuse both the host planes and the
        device-resident copies (zero re-encode, zero re-transfer)."""
        fp = (
            frozen.fingerprint(),
            tuple(resource_names),
            tuple(n.name for n in self.existing_nodes),
            tuple(id(n) for n in self.existing_nodes),
        )
        if len(self._fp_ids) > 64:  # interner bound (fp tuples are large)
            self._fp_ids.clear()
            self._fp_cache.clear()
            self._row_cache.clear()
            self._batch_cache.clear()
        fpid = self._fp_ids.setdefault(fp, len(self._fp_ids))
        e = self._fp_cache.get(fpid)
        if e is not None:
            return e, fpid

        catalog = self._catalog_union()
        T, S, E = len(catalog), len(self.templates), len(self.existing_nodes)
        # T == 0 (existing-capacity-only solve) keeps a dummy never-viable
        # IT axis so reductions over T stay well-formed; same for the
        # template axis S (gathers on a zero-size axis are invalid)
        pad_T, pad_S = max(T, 1), max(S, 1)
        K, V = frozen.K, frozen.V
        R = len(resource_names)

        well_known = np.array(
            [k in apilabels.WELL_KNOWN_LABELS for k in frozen.key_names],
            dtype=bool,
        )

        # Integer-unit quantization: the device planes hold integer-valued
        # float32 (milli-units for cpu and counts, Mi for memory-like
        # resources), so every in-kernel sum/difference/division is EXACT
        # below 2^24 and exact-boundary fits are neither rejected (the old
        # K_MARGIN shaved floor((alloc-req)/r) by one at exact fits,
        # opening a fresh node where the greedy oracle's float64 math packs
        # the last pod) nor spuriously accepted. Requests round UP,
        # capacity rounds DOWN — the device stays conservative at sub-unit
        # granularity and the float64 decode refit repairs any residual
        # optimism. cpu is the only fractional k8s resource
        # (milli-granular); memory and hugepages quantize to Mi (exact up
        # to 2^24 Mi = 16 TiB per slot sum), ephemeral-storage to Gi
        # (NVMe-dense nodes reach tens of TB; Gi keeps them far under
        # 2^24); everything else (pods, integral extended resources) keeps
        # unit granularity so the 24-bit exact-integer headroom isn't
        # burned on a pointless inflation.
        _MI, _GI = 2.0**20, 2.0**30
        quant = np.array(
            [
                _GI
                if n == "ephemeral-storage"
                else _MI
                if n == "memory" or n.startswith("hugepages-")
                else 1e-3
                if n == "cpu"
                else 1.0
                for n in resource_names
            ],
            dtype=np.float64,
        )
        # the exactness invariant the margin-free kernel floor rests on:
        # quantized values must stay integer-representable in float32.
        # Clamping is the enforcement — capacity clamps low (conservative),
        # and a clamped request exceeds every real node anyway; the float64
        # decode refit repairs either direction.
        _QMAX = float(2**24 - 1)

        def _qraw(rl: dict) -> np.ndarray:
            raw = np.array(
                [rl.get(n, 0.0) for n in resource_names], dtype=np.float64
            )
            return raw / quant

        def rvec(rl: dict) -> np.ndarray:
            """Requests-side quantization (ceil)."""
            x = np.ceil(_qraw(rl) * (1.0 - 1e-12) - 1e-9)
            return np.minimum(x, _QMAX).astype(np.float32)

        def rvec_cap(rl: dict) -> np.ndarray:
            """Capacity-side quantization (floor)."""
            x = np.floor(_qraw(rl) * (1.0 + 1e-12) + 1e-9)
            return np.minimum(x, _QMAX).astype(np.float32)

        def rvec64q(rl: dict) -> np.ndarray:
            """Requests-side quantization, float64 (ceil, unclamped)."""
            return np.ceil(_qraw(rl) * (1.0 - 1e-12) - 1e-9)

        def rvec64q_cap(rl: dict) -> np.ndarray:
            """Capacity-side quantization, float64 (floor, unclamped)."""
            return np.floor(_qraw(rl) * (1.0 + 1e-12) + 1e-9)

        from karpenter_core_tpu.solver.vocab import encode_requirements_batch

        it_masks = encode_requirements_batch(
            frozen, [it.requirements for it in catalog]
        )
        tmpl_masks = _neutralize(
            encode_requirements_batch(
                frozen, [t.requirements for t in self.templates]
            )
        )
        if S == 0:  # dummy neutral template row (never selected)
            tmpl_masks = EntityMasks(
                mask=np.ones((pad_S, K, V), dtype=bool),
                defines=np.zeros((pad_S, K), dtype=bool),
                concrete=np.zeros((pad_S, K), dtype=bool),
                negative=np.ones((pad_S, K), dtype=bool),
                gt=np.full((pad_S, K), GT_NONE, dtype=np.int32),
                lt=np.full((pad_S, K), LT_NONE, dtype=np.int32),
            )

        it_alloc = np.zeros((pad_T, R), dtype=np.float32)
        it_alloc64q = np.zeros((pad_T, R), dtype=np.float64)
        for ti, it in enumerate(catalog):
            it_alloc[ti] = rvec_cap(it.allocatable())
            it_alloc64q[ti] = rvec64q_cap(it.allocatable())

        # offerings tensor [T, Z, CT] over the zone/ct vocab rows
        zone_kid = frozen.keys.get(apilabels.LABEL_TOPOLOGY_ZONE, 0)
        ct_kid = frozen.keys.get(apilabels.CAPACITY_TYPE_LABEL_KEY, 0)
        Z = max(len(frozen.value_names[zone_kid]), 1)
        CT = max(len(frozen.value_names[ct_kid]), 1)
        off_avail = np.zeros((pad_T, Z, CT), dtype=bool)
        # relaxsolve price planes (ops/relax.py): per-IT min AVAILABLE
        # offering price (the relaxation's $/pod numerator), ICE'd rows
        # excluded exactly like the availability mask
        _PRICE_NONE = np.float32(1e12)  # == ops/relax.BIG_PRICE
        it_price = np.full((pad_T,), _PRICE_NONE, dtype=np.float32)
        for ti, it in enumerate(catalog):
            for off in it.offerings:
                if not off.available:
                    continue
                # the unavailable-offerings tensor mask: ICE'd rows never
                # enter fresh-node viability (apply_unavailable already
                # flipped copies' available flags; this guards catalogs
                # handed in pre-built, e.g. over the sidecar wire)
                if off.key(it.name) in self.unavailable_offerings:
                    continue
                it_price[ti] = min(it_price[ti], np.float32(off.price))
                z = frozen.values[zone_kid].get(off.zone)
                c_ = frozen.values[ct_kid].get(off.capacity_type)
                if z is not None and c_ is not None:
                    off_avail[ti, z, c_] = True

        # template-IT viability from the host prefilter (exact reference
        # path)
        it_index = {id(it): i for i, it in enumerate(catalog)}
        tmpl_it = np.zeros((pad_S, pad_T), dtype=bool)
        for si, t in enumerate(self.templates):
            for it in t.instance_type_options:
                tmpl_it[si, it_index[id(it)]] = True
        # per-template min node price (the scored-fallback comparator's
        # $-cost proxy): the cheapest priced IT the template could open
        tmpl_price = np.full((pad_S,), _PRICE_NONE, dtype=np.float32)
        for si in range(S):
            viable = tmpl_it[si]
            if viable.any():
                tmpl_price[si] = float(
                    np.min(np.where(viable, it_price, _PRICE_NONE))
                )
        tmpl_overhead = np.stack(
            [rvec(o) for o in self.daemon_overhead]
        ) if S else np.zeros((pad_S, R), dtype=np.float32)
        tmpl_overhead64q = np.stack(
            [rvec64q(o) for o in self.daemon_overhead]
        ) if S else np.zeros((pad_S, R), dtype=np.float64)

        # existing-node init rows (seeded into slot rows [0, E) each round)
        exist_masks = (
            _neutralize(encode_requirements_batch(frozen, self._exist_reqs()))
            if E
            else None
        )
        ex_valmask = np.ones((E, K, V), dtype=bool)
        ex_defines = np.zeros((E, K), dtype=bool)
        ex_complement = np.ones((E, K), dtype=bool)
        ex_negative = np.ones((E, K), dtype=bool)
        ex_gt = np.full((E, K), GT_NONE, dtype=np.int32)
        ex_lt = np.full((E, K), LT_NONE, dtype=np.int32)
        ex_requests = np.zeros((E, R), dtype=np.float32)
        ex_capacity = np.zeros((E, R), dtype=np.float32)
        for ei, node in enumerate(self.existing_nodes):
            # same arithmetic as ExistingNodeSim: daemon overhead minus the
            # node's own daemon requests, floored at zero
            remaining = resutil.subtract(
                self._node_daemon_overhead(node), node.daemon_requests
            )
            for k_ in list(remaining):
                if remaining[k_] < 0:
                    remaining[k_] = 0.0
            ex_requests[ei] = rvec(remaining)
            ex_capacity[ei] = rvec_cap(node.available)
            ex_valmask[ei] = exist_masks.mask[ei]
            ex_defines[ei] = exist_masks.defines[ei]
            ex_complement[ei] = np.where(
                exist_masks.defines[ei], ~exist_masks.concrete[ei], True
            )
            ex_negative[ei] = np.where(
                exist_masks.defines[ei], exist_masks.negative[ei], True
            )
            ex_gt[ei] = exist_masks.gt[ei]
            ex_lt[ei] = exist_masks.lt[ei]

        # -- shape bucketing (the jit-cache / compile-cliff defense) -------
        # Padded entities are inert by construction: keys/values pad to the
        # neutral invariant (all-True slot valmask, False class/template
        # masks under defines=False), instance types/templates pad
        # never-viable, topology groups pad owner/sel=False, resources pad
        # zero-request. The kernel runs at padded shapes; _solve_once
        # slices outputs back to natural sizes before decode.
        Kp = _bucket(K)
        Vp = _bucket(V)
        Tp = _bucket(pad_T)
        Sp = _bucket(pad_S, lo=2)
        Rp = _bucket(R, lo=4)

        def pad_masks(mask, defines_, concrete_like_complement, negative_,
                      gt_, lt_):
            """Pad one entity-mask family: V/K axes of the value mask pad
            False then re-neutralize where defines is False."""
            m2 = _pad(mask, {mask.ndim - 2: Kp, mask.ndim - 1: Vp}, False)
            d2 = _pad(defines_, {defines_.ndim - 1: Kp}, False)
            m2 = np.where(d2[..., None], m2, True)
            c2 = _pad(concrete_like_complement,
                      {concrete_like_complement.ndim - 1: Kp}, True)
            n2 = _pad(negative_, {negative_.ndim - 1: Kp}, True)
            g2 = _pad(gt_, {gt_.ndim - 1: Kp}, GT_NONE)
            l2 = _pad(lt_, {lt_.ndim - 1: Kp}, LT_NONE)
            return m2, d2, c2, n2, g2, l2

        tm_mask, tm_def, tm_comp, tm_neg, tm_gt, tm_lt = pad_masks(
            tmpl_masks.mask,
            tmpl_masks.defines,
            np.where(tmpl_masks.defines, ~tmpl_masks.concrete, True),
            np.where(tmpl_masks.defines, tmpl_masks.negative, True),
            tmpl_masks.gt,
            tmpl_masks.lt,
        )

        e = dict(
            fp=fp,
            resource_names=list(resource_names),
            quant=quant,
            rvec=rvec, rvec_cap=rvec_cap,
            rvec64q=rvec64q, rvec64q_cap=rvec64q_cap,
            it_masks=it_masks,
            tmpl_masks=tmpl_masks,
            tmpl_mask_np=tmpl_masks.mask,
            it_alloc=it_alloc, it_alloc64q=it_alloc64q,
            off_avail=off_avail, tmpl_it=tmpl_it,
            tmpl_overhead=tmpl_overhead, tmpl_overhead64q=tmpl_overhead64q,
            tmpl_zone_mask=tmpl_masks.mask[:, zone_kid, :Z],
            tmpl_ct_mask=tmpl_masks.mask[:, ct_kid, :CT],
            zone_kid=zone_kid, ct_kid=ct_kid, Z=Z, CT=CT,
            K=K, V=V, R=R, T=T, S=S, E=E, pad_T=pad_T, pad_S=pad_S,
            Kp=Kp, Vp=Vp, Tp=Tp, Sp=Sp, Rp=Rp,
            well_known=well_known,
            ex_valmask=ex_valmask, ex_defines=ex_defines,
            ex_complement=ex_complement, ex_negative=ex_negative,
            ex_gt=ex_gt, ex_lt=ex_lt,
            ex_requests=ex_requests, ex_capacity=ex_capacity,
            it_price=it_price,
            tmpl_price=tmpl_price,
            # device-resident copies (reused across solves via this cache)
            it_alloc_d=self._dev(_pad(it_alloc, {0: Tp, 1: Rp}, 0.0)),
            it_price_d=self._dev(
                _pad(it_price, {0: Tp}, float(_PRICE_NONE))
            ),
            tmpl_price_d=self._dev(
                _pad(tmpl_price, {0: Sp}, float(_PRICE_NONE))
            ),
            off_avail_d=self._dev(_pad(off_avail, {0: Tp}, False)),
            zone_key_d=jnp.int32(zone_kid),
            ct_key_d=jnp.int32(ct_kid),
            tm_mask_d=self._dev(_pad(tm_mask, {0: Sp}, True)),
            tm_def_d=self._dev(_pad(tm_def, {0: Sp}, False)),
            tm_comp_d=self._dev(_pad(tm_comp, {0: Sp}, True)),
            tm_neg_d=self._dev(_pad(tm_neg, {0: Sp}, True)),
            tm_gt_d=self._dev(_pad(tm_gt, {0: Sp}, GT_NONE)),
            tm_lt_d=self._dev(_pad(tm_lt, {0: Sp}, LT_NONE)),
            tmpl_it_d=self._dev(_pad(tmpl_it, {0: Sp, 1: Tp}, False)),
            tmpl_overhead_d=self._dev(
                _pad(tmpl_overhead, {0: Sp, 1: Rp}, 0.0)
            ),
            well_known_pad_d=self._dev(_pad(well_known, {0: Kp}, False)),
            well_known_d=self._dev(well_known),
            # natural-shape entity planes for the compat kernels
            im_planes_d=tuple(
                self._dev(np.asarray(x))
                for x in (
                    it_masks.mask, it_masks.defines, it_masks.concrete,
                    it_masks.negative, it_masks.gt, it_masks.lt,
                )
            ) if T else None,
            tm_planes_d=tuple(
                self._dev(np.asarray(x))
                for x in (
                    tmpl_masks.mask, tmpl_masks.defines, tmpl_masks.concrete,
                    tmpl_masks.negative, tmpl_masks.gt, tmpl_masks.lt,
                )
            ),
        )
        if len(self._fp_cache) >= self._FP_CACHE_CAP:
            old = next(iter(self._fp_cache))
            del self._fp_cache[old]
            # graftlint: disable=GL201 -- cache eviction rebuilds; dict->
            # dict filters preserve insertion order and mint no ids
            self._row_cache = {
                k: v for k, v in self._row_cache.items() if k[0] != old
            }
            # graftlint: disable=GL201 -- order-preserving filter, no ids
            self._batch_cache = {
                k: v for k, v in self._batch_cache.items() if k[0] != old
            }
        self._fp_cache[fpid] = e
        return e, fpid

    def _plan_digest(self, plan: topoplan.TopoPlan) -> bytes:
        """Content digest of the lowered topology plan — everything the
        class batch (owner/sel incidence, water-fill steps, domain ranks)
        bakes into its tensors. zcount0 is deliberately excluded: live
        domain counts feed init_state, which is rebuilt every round."""
        import hashlib

        h = hashlib.sha1()
        for a in (
            plan.h_type, plan.h_skew, plan.h_sel, plan.h_owner,
            plan.z_type, plan.z_skew, plan.z_key, plan.z_mindom,
            plan.z_sel, plan.z_owner, plan.z_domains, plan.z_rank,
        ):
            h.update(b"|")
            if a is not None:
                h.update(np.ascontiguousarray(a).tobytes())
        for s in plan.steps:
            h.update(
                (
                    f";{s.class_idx},{s.sub_value},{int(s.sub_first)},"
                    f"{int(s.sub_last)},{s.wf_group},{s.wf_key}"
                ).encode()
            )
            if s.zone_rest is not None:
                h.update(np.ascontiguousarray(s.zone_rest).tobytes())
        return h.digest()

    def _class_batch(
        self,
        fpid: int,
        frozen,
        entry: dict,
        plan: topoplan.TopoPlan,
        classes: List[PodClass],
        N: int,
    ) -> dict:
        """Stacked per-class tensors + the device compat/viability results.

        Cached on (fingerprint, slot count, plan digest, ordered class
        signature+count tuple): a steady-state re-solve — including every
        sidecar RPC with an unchanged cluster — returns the whole batch
        (and its device-resident ClassStep, attached by _class_steps)
        without touching numpy. Relaxation rounds miss here but hit the
        per-class row cache for every class the relax did NOT mutate."""
        digest = self._plan_digest(plan)
        sig_tuple = tuple((cls.signature, cls.count) for cls in classes)
        key = (fpid, N, digest, sig_tuple)
        from karpenter_core_tpu.metrics import wiring as m

        b = self._batch_cache.get(key)
        if b is not None:
            self._stat_inc("prep_cache_hits")
            m.SOLVER_PREP_CACHE.inc({"outcome": "hit"})
            return b
        self._stat_inc("prep_cache_misses")
        m.SOLVER_PREP_CACHE.inc({"outcome": "miss"})

        from karpenter_core_tpu.scheduling.requirements import (
            has_preferred_node_affinity,
        )
        from karpenter_core_tpu.solver.vocab import encode_requirements_batch

        C = len(classes)
        K, V, R = entry["K"], entry["V"], entry["R"]
        T, S, E = entry["T"], entry["S"], entry["E"]
        Kp, Vp, Tp, Sp, Rp = (
            entry["Kp"], entry["Vp"], entry["Tp"], entry["Sp"], entry["Rp"]
        )
        Z, CT = entry["Z"], entry["CT"]
        zone_kid, ct_kid = entry["zone_kid"], entry["ct_kid"]

        rows: List[Optional[dict]] = []
        miss: List[int] = []
        for i, cls in enumerate(classes):
            r = self._row_cache.get((fpid, cls.signature))
            rows.append(r)
            if r is None:
                miss.append(i)
        if miss:
            enc = encode_requirements_batch(
                frozen, [classes[i].requirements for i in miss]
            )
            # strict (pod_domains) masks — what topology admissibility
            # consults (topology.go:166-188 passes strict reqs when
            # preferences exist)
            strict_enc = encode_requirements_batch(
                frozen,
                [
                    classes[i].strict_requirements
                    if classes[i].pods
                    and has_preferred_node_affinity(classes[i].pods[0])
                    else classes[i].requirements
                    for i in miss
                ],
            )
            for j, i in enumerate(miss):
                cls = classes[i]
                req = resutil.requests_for_pods(cls.pods[0])
                row = dict(
                    mask=enc.mask[j],
                    defines=enc.defines[j],
                    concrete=enc.concrete[j],
                    negative=enc.negative[j],
                    gt=enc.gt[j],
                    lt=enc.lt[j],
                    smask=np.where(
                        strict_enc.defines[j][:, None], strict_enc.mask[j],
                        True,
                    ),
                    req=entry["rvec"](req),
                    req64=entry["rvec64q"](req),
                    taint_ok=np.array(
                        [
                            _tolerates_taints(cls.tolerations, t.taints)
                            for t in self.templates
                        ],
                        dtype=bool,
                    ),
                    exist_taint_ok=np.array(
                        [
                            _tolerates_taints(cls.tolerations, n.taints)
                            for n in self.existing_nodes
                        ],
                        dtype=bool,
                    ),
                )
                self._row_cache[(fpid, cls.signature)] = row
                rows[i] = row
            if len(self._row_cache) > self._ROW_CACHE_CAP:
                self._row_cache.clear()

        if C:
            class_masks = _neutralize(
                EntityMasks(
                    mask=np.stack([r["mask"] for r in rows]),
                    defines=np.stack([r["defines"] for r in rows]),
                    concrete=np.stack([r["concrete"] for r in rows]),
                    negative=np.stack([r["negative"] for r in rows]),
                    gt=np.stack([r["gt"] for r in rows]),
                    lt=np.stack([r["lt"] for r in rows]),
                )
            )
            smask = np.stack([r["smask"] for r in rows])
            class_requests = np.stack([r["req"] for r in rows])
            class_requests64q = np.stack([r["req64"] for r in rows])
        else:
            class_masks = EntityMasks(
                mask=np.ones((0, K, V), dtype=bool),
                defines=np.zeros((0, K), dtype=bool),
                concrete=np.zeros((0, K), dtype=bool),
                negative=np.ones((0, K), dtype=bool),
                gt=np.full((0, K), GT_NONE, dtype=np.int32),
                lt=np.full((0, K), LT_NONE, dtype=np.int32),
            )
            smask = np.ones((0, K, V), dtype=bool)
            class_requests = np.zeros((0, R), dtype=np.float32)
            class_requests64q = np.zeros((0, R), dtype=np.float64)

        taint_ok = (
            np.stack([r["taint_ok"] for r in rows])
            if C and S
            else np.zeros((C, entry["pad_S"]), dtype=bool)
        )
        exist_taint_ok = np.ones((C, N), dtype=bool)
        if C and E:
            exist_taint_ok[:, :E] = np.stack(
                [r["exist_taint_ok"] for r in rows]
            )

        Cp = _bucket(C)

        def cpad(a, fill):
            return _pad(a, {0: Cp}, fill)

        cm = class_masks
        # Fresh-node viability + kstar per class, ON DEVICE (ops/masks
        # fresh_viability) over the BUCKETED arrays, so drifting
        # template/catalog/resource counts reuse the jit entry like every
        # other kernel: the compat results never detour through the host,
        # and the solve's only device sync is the post-scan output fetch.
        # Dead-on equal to the retired host loop: same quantized float32
        # floor arithmetic, first-template-wins (pad rows carry tmpl_ok
        # False and can never be chosen).
        if C and S and T:
            cmask_p = np.where(
                cpad(cm.defines, False)[:, :, None], cpad(cm.mask, False),
                True,
            )
            class_args = (
                self._dev(cmask_p),
                self._dev(cpad(cm.defines, False)),
                self._dev(cpad(cm.concrete, False)),
                self._dev(cpad(cm.negative, True)),
                self._dev(cpad(cm.gt, GT_NONE)),
                self._dev(cpad(cm.lt, LT_NONE)),
            )
            class_it_dev = mops.intersects(*class_args, *entry["im_planes_d"])
            tmpl_compat_dev = mops.compatible(
                *class_args, *entry["tm_planes_d"], entry["well_known_d"]
            )
            class_it_b = jnp.pad(
                class_it_dev,
                ((0, 0), (0, Tp - class_it_dev.shape[1])),
            ) if class_it_dev.shape[1] < Tp else class_it_dev
            tmpl_ok_b = self._dev(
                _pad(taint_ok, {0: Cp, 1: Sp}, False)
            ) & jnp.pad(
                tmpl_compat_dev,
                ((0, 0), (0, Sp - tmpl_compat_dev.shape[1])),
            )
            # same-node-template gang co-location (gangsched, ISSUE 10):
            # AND-reduce template viability within each such gang BEFORE
            # fresh_viability's first-template-wins choice, so every
            # member resolves to the same template by construction. The
            # n_tmpl_gangs == 0 gate keeps plain problems off the extra
            # kernel entirely (byte parity).
            tmpl_gang_id, n_tmpl_gangs = _same_template_gang_ids(classes, Cp)
            gang_id_d = None
            if n_tmpl_gangs:
                gang_id_d = self._dev(tmpl_gang_id)
                tmpl_ok_b = mops.gang_joint_templates(
                    tmpl_ok_b, gang_id_d, num_gangs=n_tmpl_gangs,
                )
            cz = self._dev(cpad(cm.mask[:, zone_kid, :Z], False))
            cct = self._dev(cpad(cm.mask[:, ct_kid, :CT], False))
            tz = self._dev(_pad(entry["tmpl_zone_mask"], {0: Sp}, False))
            tct = self._dev(_pad(entry["tmpl_ct_mask"], {0: Sp}, False))
            creq = self._dev(cpad(_pad(class_requests, {1: Rp}, 0.0), 0.0))
            new_template, kstar = mops.fresh_viability(
                class_it_b,
                tmpl_ok_b,
                entry["tmpl_it_d"],
                cz, cct, tz, tct,
                entry["off_avail_d"],
                entry["it_alloc_d"],
                entry["tmpl_overhead_d"],
                creq,
            )
            if self.solver_mode == "relax":
                # relaxsolve constraint planes (ops/relax.py), cached on
                # the class batch alongside the FFD viability results —
                # warm re-solves (and every verdict-cached dispatch)
                # rebuild nothing. Same-template gangs AND-reduce the
                # relax support like the FFD mask, so the consensus rows
                # iterate over identical feasible sets. Hostname-keyed
                # topology (spread maxSkew / anti-affinity) lowers to a
                # per-class pods-per-host cap so host-floor classes never
                # estimate dense nodes they cannot fill.
                kcap = np.full((C,), BIGI, dtype=np.int32)
                for gi in range(plan.Gh):
                    ht = int(plan.h_type[gi])
                    if ht == 2:  # affinity: no per-host count cap
                        continue
                    cap = 1 if ht == 1 else max(int(plan.h_skew[gi]), 1)
                    owned = plan.h_owner[:, gi]
                    kcap[owned] = np.minimum(kcap[owned], cap)
                viable_r, k_cs_r, k_node_r, podcost_r = (
                    relax_ops.relax_viability(
                        class_it_b, tmpl_ok_b, entry["tmpl_it_d"],
                        cz, cct, tz, tct,
                        entry["off_avail_d"], entry["it_alloc_d"],
                        entry["tmpl_overhead_d"], creq,
                        entry["it_price_d"],
                        self._dev(cpad(kcap, BIGI)),
                    )
                )
                if n_tmpl_gangs:
                    viable_r = mops.gang_joint_templates(
                        viable_r, gang_id_d, num_gangs=n_tmpl_gangs,
                    )
                relax_planes = dict(
                    viable=viable_r,
                    k_cs=k_cs_r,
                    k_node=k_node_r,
                    podcost=podcost_r,
                    counts=self._dev(
                        cpad(
                            np.array(
                                [c.count for c in classes],
                                dtype=np.float32,
                            ),
                            0.0,
                        )
                    ),
                    gang_id=self._dev(tmpl_gang_id),
                    n_gangs=n_tmpl_gangs,
                )
            else:
                relax_planes = None
            class_it = class_it_b  # [Cp, Tp] device-resident
            tmpl_ok = tmpl_ok_b  # [Cp, Sp] device-resident
        else:
            class_it = jnp.zeros((Cp, Tp), dtype=bool)
            tmpl_ok = jnp.zeros((Cp, Sp), dtype=bool)
            new_template = jnp.full((Cp,), -1, dtype=jnp.int32)
            kstar = jnp.zeros((Cp,), dtype=jnp.int32)
            relax_planes = None

        b = dict(
            relax=relax_planes,
            class_masks=class_masks,
            smask=smask,
            class_requests=class_requests,
            class_requests64q=class_requests64q,
            taint_ok=taint_ok,
            exist_taint_ok=exist_taint_ok,
            class_it=class_it,
            tmpl_ok=tmpl_ok,
            new_template=new_template,
            kstar=kstar,
            Cp=Cp,
            class_steps=None,
            step_class=None,
        )
        if len(self._batch_cache) >= self._BATCH_CACHE_CAP:
            del self._batch_cache[next(iter(self._batch_cache))]
        self._batch_cache[key] = b
        return b

    def _make_init_state(
        self,
        entry: dict,
        plan: topoplan.TopoPlan,
        N: int,
        hcount0: np.ndarray,
        Ghp: int,
        Gzp: int,
    ) -> SlotState:
        """Fresh device SlotState with existing nodes seeded in rows
        [0, E). Rebuilt every round from the fp entry's cached host rows:
        ffd_solve_donated consumes the previous round's buffers in place,
        so they can never be reused across dispatches."""
        K, V, R = entry["K"], entry["V"], entry["R"]
        E = entry["E"]
        Kp, Vp, Tp, Rp = entry["Kp"], entry["Vp"], entry["Tp"], entry["Rp"]

        valmask = np.ones((N, K, V), dtype=bool)
        defines = np.zeros((N, K), dtype=bool)
        complement = np.ones((N, K), dtype=bool)
        negative = np.ones((N, K), dtype=bool)
        gt = np.full((N, K), GT_NONE, dtype=np.int32)
        lt = np.full((N, K), LT_NONE, dtype=np.int32)
        requests = np.zeros((N, R), dtype=np.float32)
        capacity = np.full((N, R), np.float32(BIG))
        kind = np.zeros((N,), dtype=np.int8)
        template_arr = np.full((N,), -1, dtype=np.int32)
        if E:
            valmask[:E] = entry["ex_valmask"]
            defines[:E] = entry["ex_defines"]
            complement[:E] = entry["ex_complement"]
            negative[:E] = entry["ex_negative"]
            gt[:E] = entry["ex_gt"]
            lt[:E] = entry["ex_lt"]
            requests[:E] = entry["ex_requests"]
            capacity[:E] = entry["ex_capacity"]
            kind[:E] = 1

        # slot valmask pads True everywhere: defined keys re-acquire False
        # pad columns on first intersection with a (False-padded) class
        # mask; EXISTING slots' defined keys must pad False now or
        # anti-affinity rowcounts see phantom values
        valmask_p = _pad(valmask, {1: Kp, 2: Vp}, True)
        defines_p = _pad(defines, {1: Kp}, False)
        valmask_p[:, :K] = np.where(
            defines[:, :K, None],
            _pad(valmask, {2: Vp}, False)[:, :K],
            valmask_p[:, :K],
        )
        # slot-axis planes land pre-sharded over the mesh (_dev_slots,
        # matching parallel.mesh.SLOT_STATE_SPECS); zcount and the head
        # scalars replicate — the same classification slot_shardings pins
        return SlotState(
            valmask=self._dev_slots(valmask_p),
            defines=self._dev_slots(defines_p),
            complement=self._dev_slots(_pad(complement, {1: Kp}, True)),
            negative=self._dev_slots(_pad(negative, {1: Kp}, True)),
            gt=self._dev_slots(_pad(gt, {1: Kp}, GT_NONE)),
            lt=self._dev_slots(_pad(lt, {1: Kp}, LT_NONE)),
            itmask=self._dev_slots(np.zeros((N, Tp), dtype=bool)),
            requests=self._dev_slots(_pad(requests, {1: Rp}, 0.0)),
            capacity=self._dev_slots(_pad(capacity, {1: Rp}, np.float32(BIG))),
            kind=self._dev_slots(kind),
            template=self._dev_slots(template_arr),
            podcount=self._dev_slots(np.zeros((N,), dtype=np.int32)),
            next_free=jnp.int32(E),
            overflow=jnp.asarray(False),
            hcount=self._dev_slots(_pad(hcount0, {1: Ghp}, 0)),
            zcount=self._dev(_pad(plan.zcount0, {0: Gzp, 1: Vp}, 0)),
            carry=jnp.int32(0),
        )

    def _prepare_with_vocab(
        self, plan: topoplan.TopoPlan, max_slots, topo: Topology
    ) -> _Prepared:
        """Assemble the device problem, reusing every tensor the pod mix
        did not invalidate.

        Three cache layers (see __init__) make re-solves incremental: the
        canonical vocab fingerprint keys the catalog/template/existing-node
        tensors (_fp_entry); per-class rows key on the class signature so
        a relaxation round re-encodes only the classes the relax mutated;
        and the stacked class batch — host planes plus the device-resident
        compat/viability results and the scanned ClassStep — keys on the
        ordered signature+count tuple and the topology-plan digest, so a
        steady-state re-solve skips the numpy rebuild entirely. Only
        genuinely per-round state is rebuilt every call: the plan lowering,
        the live count seeds (hcount0/zcount0), and init_state, whose
        device buffers are donated to the kernel and cannot outlive one
        dispatch."""
        classes = plan.device_classes
        catalog = self._catalog_union()
        E = len(self.existing_nodes)
        # the sharded slot axis must divide evenly across the mesh
        # (device_put rejects uneven shards); padded slots are inert by
        # construction, so the packing is invariant (parity-tested)
        N = pmesh.pad_to_devices(max_slots, self.devices)
        if E > N:
            raise _SlotOverflow()

        frozen = self._build_vocab(classes, plan)
        self._round_frozen = frozen
        topoplan.finalize_arrays(plan, frozen, topo)
        resource_names = self._resource_axis(classes)
        entry, fpid = self._fp_entry(frozen, resource_names)
        batch = self._class_batch(fpid, frozen, entry, plan, classes, N)

        K, V = frozen.K, frozen.V
        Ghp = _bucket(plan.Gh, lo=1)
        Gzp = _bucket(plan.Gz, lo=1)
        Vp = entry["Vp"]
        self._pad_shapes = dict(
            K=K, V=V, T=entry["pad_T"], Gh=plan.Gh, Gz=plan.Gz
        )

        # per-round existing-node sims (they register with this round's
        # topology); their encoded rows come from the fp entry
        existing_sims = [
            ExistingNodeSim(node, topo, self._node_daemon_overhead(node))
            for node in self.existing_nodes
        ]

        # topology count state: hostname-group counts seeded per existing
        # slot; positive counts on non-slot hostnames only matter for the
        # affinity bootstrap check (h_possel0)
        slot_names = [n.name for n in self.existing_nodes]
        hcount0 = topoplan.initial_hcounts(plan, slot_names, N).T  # [N, Gh]
        slot_name_set = set(slot_names)
        h_possel0 = np.zeros((plan.Gh,), dtype=bool)
        for gi, dg in enumerate(plan.host_groups):
            # graftlint: disable=GL201 -- any() over domain counts is an
            # order-insensitive reduction (and short-circuits; sorting
            # would force materializing every domain)
            h_possel0[gi] = any(
                cnt > 0
                for name, cnt in dg.group.domains.items()
                if name not in slot_name_set
            )

        statics = FFDStatics(
            it_alloc=entry["it_alloc_d"],
            off_avail=entry["off_avail_d"],
            zone_key=entry["zone_key_d"],
            ct_key=entry["ct_key_d"],
            tmpl_mask=entry["tm_mask_d"],
            tmpl_defines=entry["tm_def_d"],
            tmpl_complement=entry["tm_comp_d"],
            tmpl_negative=entry["tm_neg_d"],
            tmpl_gt=entry["tm_gt_d"],
            tmpl_lt=entry["tm_lt_d"],
            tmpl_it=entry["tmpl_it_d"],
            tmpl_overhead=entry["tmpl_overhead_d"],
            well_known=entry["well_known_pad_d"],
            gt_none=jnp.int32(GT_NONE),
            lt_none=jnp.int32(LT_NONE),
            h_type=self._dev(_pad(plan.h_type, {0: Ghp}, 0)),
            h_skew=self._dev(_pad(plan.h_skew, {0: Ghp}, 0)),
            h_possel0=self._dev(_pad(h_possel0, {0: Ghp}, False)),
            z_type=self._dev(_pad(plan.z_type, {0: Gzp}, 0)),
            z_skew=self._dev(_pad(plan.z_skew, {0: Gzp}, 0)),
            z_key=self._dev(_pad(plan.z_key, {0: Gzp}, 0)),
            z_mindom=self._dev(
                _pad(plan.z_mindom, {0: Gzp}, topoplan.NO_MIN_DOMAINS)
            ),
            z_domains=self._dev(_pad(plan.z_domains, {0: Gzp, 1: Vp}, False)),
            z_rank=self._dev(_pad(plan.z_rank, {0: Gzp, 1: Vp}, RANK_NONE)),
        )

        init_state = self._make_init_state(entry, plan, N, hcount0, Ghp, Gzp)

        # level-search iterations: the water level is bounded by seeded
        # topology counts + pods in this solve
        import math

        count_bound = 2 * (
            sum(c.count for c in classes)
            + (int(plan.zcount0.max()) if plan.zcount0.size else 0)
            + (int(hcount0.max()) if hcount0.size else 0)
            + 2
        )
        # bucket to a multiple of 4 so drifting pod counts share jit cache
        level_iters = -(-max(math.ceil(math.log2(count_bound)), 4) // 4) * 4

        prep = _Prepared(
            vocab=frozen,
            resource_names=resource_names,
            catalog=catalog,
            class_masks=batch["class_masks"],
            class_requests=batch["class_requests"],
            classes=classes,
            templates=self.templates,
            class_it=batch["class_it"],
            tmpl_ok=batch["tmpl_ok"],
            new_template=batch["new_template"],
            kstar=batch["kstar"],
            statics=statics,
            init_state=init_state,
            exist_taint_ok=batch["exist_taint_ok"],
            existing_sims=existing_sims,
            n_slots=N,
            topo=topo,
            plan=plan,
            smask=batch["smask"],
            it_alloc64q=entry["it_alloc64q"],
            class_requests64q=batch["class_requests64q"],
            tmpl_overhead64q=entry["tmpl_overhead64q"],
            off_avail_np=entry["off_avail"],
            tmpl_it_np=entry["tmpl_it"],
            tmpl_mask_np=entry["tmpl_mask_np"],
            zone_kid=entry["zone_kid"],
            ct_kid=entry["ct_kid"],
            n_zones=entry["Z"],
            n_cts=entry["CT"],
            level_iters=level_iters,
            n_classes_padded=batch["Cp"],
            _batch=batch,
            # relaxsolve (ISSUE 13): the candidate dispatch rebuilds its
            # own init state (the baseline's was donated) from the same
            # cached rows; the per-template price vec ranks candidates
            init_args=(entry, plan, N, hcount0, Ghp, Gzp),
            tmpl_price_d=entry["tmpl_price_d"],
        )
        self._prepare_gangsched(prep, plan, entry, N)
        return prep

    def _prepare_gangsched(
        self, prep: _Prepared, plan: topoplan.TopoPlan, entry: dict, N: int
    ) -> None:
        """Attach the gangsched structures (ISSUE 10) to a prepared solve.

        Entirely gated on the class batch actually carrying tiers/gangs:
        plain problems leave every field at its None/empty default, so the
        dispatch below them takes the exact pre-gang kernels and produces
        byte-identical result wires."""
        classes = prep.classes
        tiers = np.array([c.tier for c in classes], dtype=np.int64)
        has_tiers = bool(len(classes)) and bool((tiers != 0).any())
        has_gangs = any(c.gang is not None for c in classes)
        if not has_tiers and not has_gangs:
            return
        C = len(classes)
        tier_of_class = np.clip(tiers, -(2**31 - 1), 2**31 - 1).astype(
            np.int32
        )
        gang_of_class = np.full((C,), gangmod.GANG_FREE, dtype=np.int32)
        if has_gangs:
            # kernel-enforced gangs: fully on the device path. A gang with
            # a member in the fallback set places through the host loop,
            # where the atomicity backstop (solver/gangs.enforce_atomicity)
            # is the enforcement — its device members must not roll back
            # for a host placement the kernel cannot see. Those members
            # carry the GANG_FALLBACK_STRADDLING sentinel: inert for the
            # atomicity kernel (which keys on >= 0) but still a gang mark,
            # so the preemption pass never evicts real workload to place a
            # member the backstop may strip (gang-free means gang_of_class
            # == gangmod.GANG_FREE exactly; solver/gangs.py single-sources
            # the sentinel domain).
            fallback_names = {
                c.gang[0]
                for c in plan.fallback_classes
                if getattr(c, "gang", None) is not None
            }
            gangs = []
            for g in gangmod.collect_gangs(classes):
                if g.name in fallback_names:
                    for ci in g.class_indices:
                        gang_of_class[ci] = gangmod.GANG_FALLBACK_STRADDLING
                else:
                    gangs.append(g)
            if gangs:
                Gp = _bucket(len(gangs), lo=1)
                gmin = np.zeros((Gp,), dtype=np.int32)
                for gi, g in enumerate(gangs):
                    gmin[gi] = g.min_count
                    for ci in g.class_indices:
                        gang_of_class[ci] = gi
                prep.gangs = gangs
                prep.gang_min = self._dev(gmin)
                self._prepare_topoaware(prep, entry, gangs, gang_of_class, N)
        prep._batch["tier_of_class"] = tier_of_class
        prep._batch["gang_of_class"] = gang_of_class
        # evictable-capacity planes for the preemption pass: positive-tier
        # demand, existing nodes with evictable bound pods, and no device
        # topology state (the documented interplay limit — a preempted
        # placement bypasses the in-kernel topology counters)
        if (
            bool((tiers > 0).any())
            and entry["E"]
            and not plan.has_device_topology()
        ):
            ev_cache = entry.setdefault("ev_planes", {})
            cached = ev_cache.get(N)
            if cached is None:
                cached = self._build_ev_planes(entry, N)
                ev_cache[N] = cached
            prep.ev, prep.ev_uids, prep.ev_freed = cached

    def _prepare_topoaware(
        self, prep: _Prepared, entry: dict, gangs, gang_of_class, N: int
    ) -> None:
        """Per-gang-class hop planes (topoaware, ISSUE 20): anchor every
        kernel gang on the rack domain with the most demand-debited
        headroom (ops/topoplan.gang_anchors) and hand its member classes
        the anchor's [N] hop-distance row as their FFD fill-level plane
        (ClassStep.topo_rank, attached by _class_steps) plus a
        per-template hop cost row for the relax objective. Engages only
        when the catalog actually carries rack labels — plan_racks
        returns None otherwise, ClassStep.topo_rank stays at its None
        default, and the kernel traces the exact pre-topo program (the
        off-by-default parity contract). The RackPlan caches on the fp
        entry per slot count: node and template labels are fp-invariant,
        only the slot axis varies."""
        rp_cache = entry.setdefault("rack_plans", {})
        if N not in rp_cache:
            rp_cache[N] = topoplan.plan_racks(
                [
                    dict(getattr(n, "labels", None) or {})
                    for n in self.existing_nodes
                ],
                # single-valued template requirements attribute a fresh
                # claim to a rack exactly like the verifier will
                [gangmod.claim_topo_labels(t) for t in self.templates],
                N,
            )
        rplan = rp_cache[N]
        if rplan is None:
            return
        anchors = topoplan.gang_anchors(
            rplan,
            [g.name for g in gangs],
            [g.min_count for g in gangs],
        )
        C = int(gang_of_class.shape[0])
        S = entry["S"]
        Sn = max(S, 1)
        topo_rank = np.zeros((C, N), dtype=np.int32)
        topo_cost = np.zeros((C, Sn), dtype=np.float32)
        for g in gangs:
            anchor = anchors[g.name]
            row = topoplan.hop_from_anchor(
                rplan, anchor, gangmod.MAX_HOP_DISTANCE
            )
            # template hop cost from the same anchor; a template without a
            # single-valued rack sits at the ceiling (uniform rows cannot
            # flip a per-class argmin, so label-free catalogs stay inert)
            th = np.full((Sn,), gangmod.MAX_HOP_DISTANCE, dtype=np.float32)
            for si in range(S):
                d = int(rplan.tmpl_domain[si])
                if d >= 0:
                    th[si] = min(
                        int(rplan.hop[anchor, d]),
                        gangmod.MAX_HOP_DISTANCE,
                    )
            for ci in g.class_indices:
                topo_rank[ci] = row
                topo_cost[ci] = th
        prep._batch["topo_rank_of_class"] = topo_rank
        prep._batch["topo_cost_of_class"] = topo_cost
        prep.topo_anchors = anchors

    def _build_ev_planes(self, entry: dict, N: int):
        """ops/gangsched.EvPlanes over the existing nodes' evictable bound
        pods: per node, cost-sorted ((disruption cost, uid) ascending —
        utils/disruption.eviction_cost's order), pod axis padded to a
        bucketed P. Returns (EvPlanes | None, uid table, freed-request
        table) — the host tables map an evicted [N, P] mask back to
        eviction claims and their freed capacity."""
        E, Rp = entry["E"], entry["Rp"]
        rvec_cap = entry["rvec_cap"]
        per_node = [
            sorted(
                getattr(n, "evictable", ()) or (),
                key=lambda e: (e.cost, e.uid),
            )
            for n in self.existing_nodes
        ]
        maxP = max((len(v) for v in per_node), default=0)
        if maxP == 0:
            return None, [], []
        P = _bucket(maxP, lo=2)
        req = np.zeros((N, P, Rp), dtype=np.float32)
        tier = np.full((N, P), BIGI, dtype=np.int32)
        cost = np.zeros((N, P), dtype=np.float32)
        valid = np.zeros((N, P), dtype=bool)
        ev_uids: List[List[str]] = []
        ev_freed: List[list] = []
        for ei in range(E):
            uids, freed = [], []
            for j, e in enumerate(per_node[ei]):
                # freed capacity floor-quantizes (capacity-side): the
                # kernel must never believe an eviction frees more than
                # the float64 decode refit will actually credit
                vec = rvec_cap(e.requests)
                req[ei, j, : vec.shape[0]] = vec
                tier[ei, j] = e.priority
                cost[ei, j] = e.cost
                valid[ei, j] = True
                uids.append(e.uid)
                freed.append(dict(e.requests))
            ev_uids.append(uids)
            ev_freed.append(freed)
        planes = gangsched.EvPlanes(
            req=req, tier=tier, cost=cost, valid=valid
        )
        return self._dev_ev(planes, N), ev_uids, ev_freed

    def _dev_ev(self, planes, n_slots: int):
        """Host->device put for the EvPlanes: slot axis pre-sharded over
        the mesh via parallel.mesh.gang_plane_shardings (the GANG_EV_SPECS
        classification GL501 resolves), replicated copies on a 1-device
        scheduler — the EvPlanes twin of _dev_slots."""
        for leaf in planes:
            self._h2d_bytes += leaf.nbytes
            if self._mesh is None:
                self._h2d_dev_bytes += leaf.nbytes
            else:
                self._h2d_dev_bytes += -(-leaf.nbytes // self.devices)
        if self._mesh is None:
            return type(planes)(*(jnp.asarray(x) for x in planes))
        return jax.device_put(
            planes,
            pmesh.gang_plane_shardings(self._mesh, planes, n_slots),
        )

    def _class_steps(self, prep: _Prepared) -> ClassStep:
        """Per-STEP scanned arrays: one step per class, except self-selecting
        label-spread classes which expand to one pinned sub-step per
        admissible domain (ops/topoplan.py). All axes pad to the bucketed
        shapes of prep.statics/init_state; steps pad to a bucketed count
        with inert entries (count=0, no viable template — the scan carries
        state through them unchanged). The finished device-resident
        ClassStep caches on the class batch (prep._batch), so steady-state
        re-solves skip both the host assembly and the host->device
        transfer."""
        cached = prep._batch.get("class_steps")
        if cached is not None:
            prep.step_class = prep._batch["step_class"]
            prep.step_tier = prep._batch.get("step_tier_d")
            prep.step_gang = prep._batch.get("step_gang_d")
            return cached
        cm = prep.class_masks
        plan = prep.plan
        steps = plan.steps
        V = prep.vocab.V
        cis = np.array([s.class_idx for s in steps], dtype=np.int32)
        counts = np.array(
            [prep.classes[ci].count for ci in cis], dtype=np.int32
        )
        J = len(steps)
        Jp = _bucket_steps(J)
        Kp = int(prep.statics.well_known.shape[0])
        Vp = int(prep.statics.z_domains.shape[1])
        Tp = int(prep.statics.it_alloc.shape[0])
        Sp = int(prep.statics.tmpl_it.shape[0])
        Rp = int(prep.statics.it_alloc.shape[1])
        Ghp = int(prep.statics.h_type.shape[0])
        Gzp = int(prep.statics.z_type.shape[0])
        zone_rest = (
            np.stack(
                [
                    s.zone_rest
                    if s.zone_rest is not None
                    else np.zeros((V,), dtype=bool)
                    for s in steps
                ]
            )
            if J
            else np.zeros((0, V), dtype=bool)
        )

        def stepvec(values, dtype, fill):
            return _pad(np.array(values, dtype=dtype), {0: Jp}, fill)

        # device-resident per-class arrays (class_it/tmpl_ok/new_template/
        # kstar live on device, see _prepare_with_vocab): gather by padded
        # step index, pad the natural T/S axes up to the statics' bucketed
        # shapes, and neutralize the pad rows so inert steps stay inert
        ci_padded = np.zeros((Jp,), dtype=np.int32)
        ci_padded[:J] = cis
        ci_j = jnp.asarray(ci_padded)
        valid_j = jnp.asarray(np.arange(Jp) < J)
        class_it_g = prep.class_it[ci_j]
        if class_it_g.shape[1] < Tp:
            class_it_g = jnp.pad(
                class_it_g, ((0, 0), (0, Tp - class_it_g.shape[1]))
            )
        tmpl_ok_g = prep.tmpl_ok[ci_j]
        if tmpl_ok_g.shape[1] < Sp:
            tmpl_ok_g = jnp.pad(
                tmpl_ok_g, ((0, 0), (0, Sp - tmpl_ok_g.shape[1]))
            )

        mask = _pad(cm.mask[cis], {0: Jp, 1: Kp, 2: Vp}, False)
        defines = _pad(cm.defines[cis], {0: Jp, 1: Kp}, False)
        mask = np.where(defines[:, :, None], mask, True)  # neutral pads
        smask = _pad(prep.smask[cis], {0: Jp, 1: Kp, 2: Vp}, True)
        # topoaware fill levels (ISSUE 20): [Jp, N] gang-anchor hop rows,
        # a second slot-axis scanned input beside exist_taint_ok — present
        # only when _prepare_topoaware engaged (rack labels + kernel
        # gangs); otherwise ClassStep.topo_rank keeps its None default and
        # the scan traces the pre-topo program (parity)
        topo_np = prep._batch.get("topo_rank_of_class")
        topo_kw = (
            {}
            if topo_np is None
            else {
                "topo_rank": self._dev_slots(
                    _pad(topo_np[cis], {0: Jp}, 0), dim=1
                )
            }
        )
        step = ClassStep(
            mask=self._dev(mask),
            defines=self._dev(defines),
            concrete=self._dev(_pad(cm.concrete[cis], {0: Jp, 1: Kp}, False)),
            negative=self._dev(_pad(cm.negative[cis], {0: Jp, 1: Kp}, True)),
            gt=self._dev(_pad(cm.gt[cis], {0: Jp, 1: Kp}, GT_NONE)),
            lt=self._dev(_pad(cm.lt[cis], {0: Jp, 1: Kp}, LT_NONE)),
            count=self._dev(_pad(counts, {0: Jp}, 0)),
            requests=self._dev(
                _pad(prep.class_requests[cis], {0: Jp, 1: Rp}, 0.0)
            ),
            class_it=jnp.where(valid_j[:, None], class_it_g, False),
            tmpl_ok=jnp.where(valid_j[:, None], tmpl_ok_g, False),
            # [Jp, N]: the one scanned input with a slot axis (dim 1) —
            # each scan step slices a slot-sharded [N] row
            exist_taint_ok=self._dev_slots(
                _pad(prep.exist_taint_ok[cis], {0: Jp}, False), dim=1
            ),
            new_template=jnp.where(valid_j, prep.new_template[ci_j], -1),
            kstar=jnp.where(valid_j, prep.kstar[ci_j], 0),
            smask=self._dev(smask),
            h_sel=self._dev(_pad(plan.h_sel[cis], {0: Jp, 1: Ghp}, False)),
            h_owner=self._dev(_pad(plan.h_owner[cis], {0: Jp, 1: Ghp}, False)),
            z_sel=self._dev(_pad(plan.z_sel[cis], {0: Jp, 1: Gzp}, False)),
            z_owner=self._dev(_pad(plan.z_owner[cis], {0: Jp, 1: Gzp}, False)),
            sub_value=self._dev(
                stepvec([s.sub_value for s in steps], np.int32, -1)
            ),
            sub_first=self._dev(
                stepvec([s.sub_first for s in steps], bool, True)
            ),
            sub_last=self._dev(
                stepvec([s.sub_last for s in steps], bool, True)
            ),
            wf_group=self._dev(
                stepvec([s.wf_group for s in steps], np.int32, -1)
            ),
            wf_key=self._dev(
                stepvec([s.wf_key for s in steps], np.int32, -1)
            ),
            zone_rest=self._dev(_pad(zone_rest, {0: Jp, 1: Vp}, False)),
            **topo_kw,
        )
        prep._batch["class_steps"] = step
        prep._batch["step_class"] = ci_j
        prep.step_class = ci_j
        # gangsched step rows (replicated device [Jp]): the class tier and
        # kernel-gang index lifted to the scanned step axis — present only
        # when the batch carries tiers/gangs (plain problems skip the
        # transfer entirely)
        tier_of_class = prep._batch.get("tier_of_class")
        if tier_of_class is not None:
            gang_of_class = prep._batch["gang_of_class"]
            prep.step_tier = self._dev(
                _pad(tier_of_class[cis], {0: Jp}, 0)
            )
            prep.step_gang = self._dev(
                # padded steps are gang-free: never preemption-eligible
                # anyway (their counts are 0), never a kernel gang
                _pad(gang_of_class[cis], {0: Jp}, gangmod.GANG_FREE)
            )
            prep._batch["step_tier_d"] = prep.step_tier
            prep._batch["step_gang_d"] = prep.step_gang
        return step

    def _catalog_union(self) -> List[InstanceType]:
        if self._catalog is None:
            seen = {}
            for t in self.templates:
                for it in t.instance_type_options:
                    seen.setdefault(id(it), it)
            # include full per-pool catalogs so class_it covers everything
            for its in self.instance_types.values():
                for it in its:
                    seen.setdefault(id(it), it)
            self._catalog = list(seen.values())
        return self._catalog

    def _node_daemon_overhead(self, node: SimNode) -> dict:
        return resutil.requests_for_pods(
            *node_daemon_pods(node, self.daemonset_pods)
        )

    # ------------------------------------------------------------------

    def _decode(
        self, prep: _Prepared, out: Dict[str, np.ndarray]
    ) -> Tuple[List[InFlightNodeClaim], List[ExistingNodeSim], list]:
        """Re-materialize device placements through the host algebra.

        Topology-free solves merge each slot's class groups with the exact
        reference-semantics machinery (Requirements.add +
        filter_instance_types). Topology solves instead reconstruct each
        fresh slot's joined requirements straight from the final device
        planes (decode_requirements — the planes already carry every
        admissibility tightening the kernel applied) and sync the host
        groups' domain counters from the device count state. Either way, any
        placement the host-side checks reject is re-placed through the host
        greedy add; only pods the host path also rejects surface as failures
        (and re-enter via relaxation)."""
        # per-class decision planes: the step->class merge already ran on
        # device (ops/ffd.aggregate_takes), so decode starts from the
        # [C, used-slots] matrix instead of replaying J scan steps
        takes_bc = np.asarray(out["takes_bc"])
        unplaced_by_class = np.asarray(out["unplaced_bc"]).astype(np.int64)
        slot_template = np.asarray(out["template"])
        plan = prep.plan
        C = len(prep.classes)
        E = len(prep.existing_sims)
        failed: list = []
        divergent: List[Pod] = []

        assigned: Dict[int, Dict[int, int]] = {}
        for ci, n in zip(*np.nonzero(takes_bc)):
            assigned.setdefault(int(n), {})[int(ci)] = int(takes_bc[ci, n])
        for ci, cls in enumerate(prep.classes):
            k_unplaced = int(unplaced_by_class[ci])
            if k_unplaced:
                for p in cls.pods[cls.count - k_unplaced :]:
                    failed.append((p, "no nodepool matched pod"))

        claims: List[InFlightNodeClaim] = []
        topo = prep.topo
        pod_cursor = {ci: 0 for ci in range(C)}

        if plan.has_device_topology():
            return self._decode_topo(
                prep, out, assigned, slot_template, pod_cursor, claims, failed
            )

        # ---- topology-free path ------------------------------------------
        # group-add is exact only when no topology group could observe these
        # pods (decode sees topology-free pods, but inverse anti-affinity
        # groups from the cluster can still select them by label)
        can_group = not topo.topologies and not topo.inverse_topologies

        for n in sorted(assigned):
            groups = sorted(assigned[n].items())
            if n < E:
                target = prep.existing_sims[n]
            else:
                si = int(slot_template[n])
                template = prep.templates[si]
                if can_group and self._decode_fresh_vectorized(
                    prep, si, template, groups, pod_cursor, topo,
                    claims, divergent,
                ):
                    continue
                target = InFlightNodeClaim(
                    template,
                    topo,
                    self.daemon_overhead[si],
                    template.instance_type_options,
                )
                claims.append(target)
            for ci, k in groups:
                cls = prep.classes[ci]
                start = pod_cursor[ci]
                pods = cls.pods[start : start + k]
                pod_cursor[ci] = start + k
                if not pods:
                    continue
                req = resutil.requests_for_pods(pods[0])
                if can_group and not pods[0].host_ports:
                    try:
                        target.add_group(pods, req)
                        continue
                    except IncompatibleError:
                        pass  # re-place pod-by-pod below
                for p in pods:
                    try:
                        target.add(p, req)
                    except IncompatibleError:
                        divergent.append(p)
        if divergent:
            from karpenter_core_tpu.metrics import wiring as m

            m.SOLVER_HOST_FALLBACK_PODS.inc(
                {"cause": "divergent"}, by=len(divergent)
            )
        for p in divergent:
            err = self._host_fallback_add(p, claims, prep.existing_sims, topo)
            if err is not None:
                failed.append((p, err))
        # drop empty claims (all groups failed), releasing their placeholder
        # hostnames from the shared per-round topology (see below)
        kept = []
        for c in claims:
            if c.pods:
                kept.append(c)
            else:
                c.destroy()
        if can_group:
            kept = self._repack_sparse_claims(kept)
        return kept, prep.existing_sims, failed

    def _repack_sparse_claims(
        self, claims: List[InFlightNodeClaim]
    ) -> List[InFlightNodeClaim]:
        """Eliminate class-batched tail fragmentation.

        The kernel opens ceil(rem/kstar) identical fresh slots per class
        (ops/ffd.py), which can strand a near-empty tail node the
        pod-at-a-time oracle never creates. Walk claims sparsest-first and
        try to re-place each one's pods into the other claims through the
        host algebra; a claim whose pods all move is dropped. Stops at the
        first claim that cannot fully drain (denser ones won't either).
        Topology-free solves only (the caller gates on can_group): moving a
        pod never touches domain counters here. A partial drain keeps the
        claim with its remaining pods — still a valid packing, requests
        intentionally left conservative (stale high) on the source."""
        if len(claims) < 2:
            return claims
        claims = sorted(claims, key=lambda c: len(c.pods))
        out = list(claims)
        for claim in claims:
            others = sorted(
                (c for c in out if c is not claim), key=lambda c: len(c.pods)
            )
            moved: List[Pod] = []
            ok = True
            for p in list(claim.pods):
                req = resutil.requests_for_pods(p)
                placed = False
                for o in others:
                    try:
                        o.add(p, req)
                        placed = True
                        break
                    except IncompatibleError:
                        continue
                if not placed:
                    ok = False
                    break
                moved.append(p)
            if not ok:
                # keep the claim with whatever didn't move; a moved pod
                # stays moved (both homes are valid, only one lists it)
                moved_ids = {id(p) for p in moved}
                claim.pods = [p for p in claim.pods if id(p) not in moved_ids]
                break
            claim.pods = []
            claim.destroy()
            out.remove(claim)
        return out

    # -- topology decode ---------------------------------------------------

    def _decode_topo(
        self,
        prep: _Prepared,
        out: Dict[str, np.ndarray],
        assigned: Dict[int, Dict[int, int]],
        slot_template: np.ndarray,
        pod_cursor: Dict[int, int],
        claims: List[InFlightNodeClaim],
        failed: list,
    ) -> Tuple[List[InFlightNodeClaim], List[ExistingNodeSim], list]:
        """Decode with device topology state: bulk commits, then host group
        count sync, then deferred per-pod replays.

        Ordering is load-bearing: deferred pods must replay through the host
        algebra AFTER the device counts (minus the deferred contributions)
        are synced into the host TopologyGroups, or they would place against
        stale counters."""
        plan, topo = prep.plan, prep.topo
        E = len(prep.existing_sims)
        valmask = np.asarray(out["valmask"])
        defines = np.asarray(out["defines"])
        complement = np.asarray(out["complement"])
        gt = np.asarray(out["gt"])
        lt = np.asarray(out["lt"])
        itmask = np.asarray(out["itmask"])
        hcount = np.asarray(out["hcount"]).astype(np.int64).copy()
        zcount = np.asarray(out["zcount"]).astype(np.int64).copy()

        deferred: List[Pod] = []
        densified = 0  # densify victims inside `deferred` (metrics split)
        # (slot, class, k, slot requirements, hostname) per bulk commit
        committed: List[tuple] = []
        slot_hostnames: Dict[int, str] = {}
        slot_claims: Dict[int, InFlightNodeClaim] = {}  # fresh slots only

        def defer(n: int, ci: int, pods: List[Pod]) -> None:
            self._topo_subtract(
                plan, valmask, defines, complement, n, ci, len(pods),
                hcount, zcount,
            )
            deferred.extend(pods)

        for n in sorted(assigned):
            groups = sorted(assigned[n].items())
            if n < E:
                target = prep.existing_sims[n]
                slot_hostnames[n] = target.name
                for ci, k in groups:
                    cls = prep.classes[ci]
                    start = pod_cursor[ci]
                    pods = cls.pods[start : start + k]
                    pod_cursor[ci] = start + k
                    if not pods:
                        continue
                    if pods[0].host_ports:
                        defer(n, ci, pods)
                        continue
                    try:
                        target.add_group(pods, resutil.requests_for_pods(pods[0]))
                        committed.append(
                            (n, ci, len(pods), target.requirements, target.name)
                        )
                    except IncompatibleError:
                        defer(n, ci, pods)
            else:
                self._commit_fresh_topo(
                    prep, n, int(slot_template[n]), groups, pod_cursor,
                    claims, committed, slot_hostnames, defer,
                    valmask, defines, complement, gt, lt, itmask,
                    slot_claims,
                )

        # Voluntary densification deferral (the topology twin of
        # _repack_sparse_claims): the class-batched kernel strands sparse
        # tail slots (ceil(rem/kstar) per class) the pod-at-a-time oracle
        # never opens. Drain the sparsest fresh slots through the existing
        # subtract-and-repair machinery — their pods re-place one-by-one
        # into the other claims' residual capacity via the host algebra,
        # re-opening an equivalent node only when nothing admits them, so
        # the pass can only densify.
        if len(slot_claims) >= 2:
            sizes = sorted(len(c.pods) for c in slot_claims.values())
            median = sizes[len(sizes) // 2]
            eligible = sorted(
                (
                    (n, c)
                    for n, c in slot_claims.items()
                    if len(c.pods) <= int(median * DENSIFY_THRESHOLD)
                ),
                key=lambda nc: len(nc[1].pods),
            )[: int(len(slot_claims) * DENSIFY_CAP)]
            victims = []
            pod_budget = DENSIFY_POD_BUDGET
            for n, c in eligible:
                if len(c.pods) > pod_budget:
                    break
                pod_budget -= len(c.pods)
                victims.append((n, c))
            if victims:
                from karpenter_core_tpu.metrics import wiring as m

                densified = sum(len(c.pods) for _, c in victims)
                m.SOLVER_HOST_FALLBACK_PODS.inc(
                    {"cause": "densify"}, by=densified
                )
            for n, claim in victims:
                for entry in [e for e in committed if e[0] == n]:
                    _n, ci, k, _reqs, _hn = entry
                    self._topo_subtract(
                        plan, valmask, defines, complement, n, ci, k,
                        hcount, zcount,
                    )
                    committed.remove(entry)
                deferred.extend(claim.pods)
                claim.pods = []
                claim.destroy()
                claims.remove(claim)
                slot_hostnames.pop(n, None)

        self._sync_topo_counts(prep, hcount, zcount, slot_hostnames)
        self._recount_host_only(prep, committed)

        if len(deferred) > densified:
            from karpenter_core_tpu.metrics import wiring as m

            m.SOLVER_HOST_FALLBACK_PODS.inc(
                {"cause": "deferred"}, by=len(deferred) - densified
            )
        for p in deferred:
            err = self._host_fallback_add(p, claims, prep.existing_sims, topo)
            if err is not None:
                failed.append((p, err))

        kept = []
        for c in claims:
            if c.pods:
                kept.append(c)
            else:
                c.destroy()
        return kept, prep.existing_sims, failed

    def _commit_fresh_topo(
        self,
        prep: _Prepared,
        n: int,
        si: int,
        groups: List[Tuple[int, int]],
        pod_cursor: Dict[int, int],
        claims: List[InFlightNodeClaim],
        committed: List[tuple],
        slot_hostnames: Dict[int, str],
        defer,
        valmask: np.ndarray,
        defines: np.ndarray,
        complement: np.ndarray,
        gt: np.ndarray,
        lt: np.ndarray,
        itmask: np.ndarray,
        slot_claims: Optional[Dict[int, InFlightNodeClaim]] = None,
    ) -> None:
        """Materialize one fresh topology slot from the final device planes:
        float64-refit the take against the slot's final viable instance
        types, rebuild the joined requirements with decode_requirements, and
        commit in bulk. minValues / hostPort shapes go per-pod instead."""
        template = prep.templates[si]
        T = len(prep.catalog)
        entries: List[Tuple[int, List[Pod]]] = []
        for ci, k in groups:
            cls = prep.classes[ci]
            start = pod_cursor[ci]
            pods = cls.pods[start : start + k]
            pod_cursor[ci] = start + k
            if pods:
                entries.append((ci, pods))
        if not entries:
            return
        plane_ok = not template.requirements.has_min_values() and all(
            not pods[0].host_ports
            and not prep.classes[ci].requirements.has_min_values()
            for ci, pods in entries
        )
        # quantized-integer refit (exact under repeated addition): the same
        # arithmetic regime as the device kernel, so a slot the kernel packed
        # exactly full is not deferred over a 1e-13 raw-float drift
        req_vec = prep.tmpl_overhead64q[si].copy()
        requests = dict(self.daemon_overhead[si])
        for ci, pods in entries:
            for _ in range(len(pods)):
                req_vec += prep.class_requests64q[ci]
            requests = resutil.merge_repeated(
                requests, resutil.requests_for_pods(pods[0]), len(pods)
            )
        opt_idx = [
            int(t)
            for t in np.nonzero(itmask[n, :T])[0]
            if np.all(req_vec <= prep.it_alloc64q[t])
        ]
        if not plane_ok or not opt_idx:
            for ci, pods in entries:
                defer(n, ci, pods)
            return
        claim = InFlightNodeClaim(
            template,
            prep.topo,
            self.daemon_overhead[si],
            [prep.catalog[t] for t in opt_idx],
        )
        reqs = decode_requirements(
            prep.vocab, valmask[n], defines[n], complement[n], gt[n], lt[n]
        )
        reqs.add(
            Requirement.new(apilabels.LABEL_HOSTNAME, "In", [claim.hostname])
        )
        claim.requirements = reqs
        claim.pods = [p for _, pods in entries for p in pods]
        claim.requests = requests
        claims.append(claim)
        slot_hostnames[n] = claim.hostname
        if slot_claims is not None:
            slot_claims[n] = claim
        for ci, pods in entries:
            committed.append((n, ci, len(pods), reqs, claim.hostname))

    @staticmethod
    def _topo_subtract(
        plan, valmask, defines, complement, n, ci, k, hcount, zcount
    ) -> None:
        """Remove a deferred placement's contributions from the device
        counts — the mirror of the kernel's count update, evaluated on the
        final planes (a slot pinned by a LATER class than the deferred one
        can over-subtract by at most the deferred pod count; deferred slots
        are divergence repairs, so the drift is bounded and rare)."""
        if plan.h_sel.size:
            hcount[n, :] -= k * plan.h_sel[ci].astype(np.int64)
        for gi in range(len(plan.label_groups)):
            if not plan.z_sel[ci, gi]:
                continue
            kid = int(plan.z_key[gi])
            if not defines[n, kid] or complement[n, kid]:
                continue
            row = valmask[n, kid]
            if plan.z_type[gi] == 1 or row.sum() == 1:
                zcount[gi] -= k * row.astype(np.int64)

    def _sync_topo_counts(
        self, prep: _Prepared, hcount, zcount, slot_hostnames: Dict[int, str]
    ) -> None:
        """Overwrite the host TopologyGroups' domain counters with the
        device truth (counts for untouched slots/domains are unchanged by
        construction, so only synced entries are written)."""
        plan = prep.plan
        for gi, dg in enumerate(plan.host_groups):
            g = dg.group
            for n, name in slot_hostnames.items():
                cnt = max(int(hcount[n, gi]), 0)
                if name not in g.domains and cnt == 0:
                    continue
                g.domains[name] = cnt
                if cnt > 0:
                    g.empty_domains.discard(name)
                else:
                    g.empty_domains.add(name)
        for gi, dg in enumerate(plan.label_groups):
            g = dg.group
            kid = int(plan.z_key[gi])
            names = prep.vocab.value_names[kid]
            # union with nonzero count columns: the kernel can record
            # placements on vocab values outside the registered universe (a
            # counted-not-constrained class pinned to an unregistered
            # domain); TopologyGroup.record creates new domain entries, so
            # the sync must too or host-fallback replays see stale counters
            cols = np.nonzero(plan.z_domains[gi] | (zcount[gi] != 0))[0]
            for vid in cols:
                name = names[vid]
                cnt = max(int(zcount[gi, vid]), 0)
                if name not in g.domains and cnt == 0:
                    continue
                g.domains[name] = cnt
                if cnt > 0:
                    g.empty_domains.discard(name)
                else:
                    g.empty_domains.add(name)

    def _recount_host_only(self, prep: _Prepared, committed: List[tuple]) -> None:
        """Groups the device could not model (non-trivial spread node
        filters) re-count the bulk-committed placements host-side at
        (class × slot) granularity — their owner classes always run on the
        host, so these counters only need the device classes' contributions."""
        plan = prep.plan
        if not plan.host_only_groups:
            return
        from karpenter_core_tpu.scheduling.requirements import (
            ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
        )

        for g in plan.host_only_groups:
            for n, ci, k, reqs, hostname in committed:
                rep = prep.classes[ci].pods[0]
                if not g.selects(rep):
                    continue
                if not g.node_filter.matches_requirements(
                    reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
                ):
                    continue
                if g.key == apilabels.LABEL_HOSTNAME:
                    domain = hostname
                else:
                    dom_req = reqs.get(g.key)
                    vals = dom_req.sorted_values()
                    if dom_req.complement or len(vals) != 1:
                        continue
                    domain = vals[0]
                g.record(*([domain] * k))

    def _decode_fresh_vectorized(
        self,
        prep: _Prepared,
        si: int,
        template,
        groups: List[Tuple[int, int]],
        pod_cursor: Dict[int, int],
        topo: Topology,
        claims: List[InFlightNodeClaim],
        divergent: List[Pod],
    ) -> bool:
        """Materialize a fresh slot's claim straight from the prep tensors.

        The per-group viability mask — template ITs ∧ class requirement
        compat (class_it, the same kernels the FFD scan used, property-tested
        against the host algebra) ∧ float64 resource fit ∧ offering
        availability under the joined zone/capacity-type masks — replaces
        the O(groups × instance-types) Python filter. Requirements and
        request dicts are still folded through the host algebra once per
        class, so the returned claim is indistinguishable from the
        add()-built one. Returns False to fall back wholesale (min-values or
        host ports in play), leaving pod cursors untouched."""
        if template.requirements.has_min_values():
            return False
        for ci, _k in groups:
            cls = prep.classes[ci]
            if cls.pods and (
                cls.pods[0].host_ports or cls.requirements.has_min_values()
            ):
                return False

        # The whole plane outcome is a pure function of the composition
        # (si, groups) given prep — and hundreds of slots repeat a handful
        # of compositions, so the per-class trial loop, request folding,
        # requirement joining, and final filter all cache on that shape;
        # per-slot work reduces to cursor advancement + claim assembly.
        shape = (si, tuple(groups))
        cached = self._composition_cache.get(shape)
        if cached is None:
            cached = self._decode_composition(prep, si, template, groups)
            self._composition_cache[shape] = cached
        committed_counts, remaining, requests_proto, reqs_proto = cached

        committed_set = {ci for ci, _ in committed_counts}
        pods_all: List[Pod] = []
        for ci, k in groups:
            cls = prep.classes[ci]
            start = pod_cursor[ci]
            pods = cls.pods[start : start + k]
            pod_cursor[ci] = start + k
            if not pods:
                continue
            if ci in committed_set and remaining:
                pods_all.extend(pods)
            else:
                divergent.extend(pods)
        if pods_all:
            claim = InFlightNodeClaim(
                template, topo, self.daemon_overhead[si], list(remaining)
            )
            claim.requirements.add(*(r.copy() for r in reqs_proto))
            claim.pods = pods_all
            claim.requests = dict(requests_proto)
            claims.append(claim)
        return True

    def _decode_composition(
        self, prep: _Prepared, si: int, template, groups: List[Tuple[int, int]]
    ):
        """Evaluate one composition shape through the plane algebra: the
        per-group viability mask — template ITs ∧ class requirement compat
        (class_it, the same kernels the FFD scan used, property-tested
        against the host algebra) ∧ quantized-integer resource fit (the
        device kernel's exact arithmetic, so slots packed exactly full are
        not rejected over raw-float drift) ∧ offering availability under
        the joined zone/capacity-type masks — then one final
        requirements-only filter_instance_types against the JOINED
        requirements (classes can be pairwise-IT-compatible yet jointly
        narrower)."""
        Z, CT = prep.n_zones, prep.n_cts
        cm = prep.class_masks
        T = len(prep.catalog)
        mask = prep.tmpl_it_np[si].copy()
        req_vec = prep.tmpl_overhead64q[si].copy()
        zmask = prep.tmpl_mask_np[si, prep.zone_kid, :Z].copy()
        ctmask = prep.tmpl_mask_np[si, prep.ct_kid, :CT].copy()
        requests = dict(self.daemon_overhead[si])
        committed_counts: List[Tuple[int, int]] = []

        for ci, k in groups:
            cls = prep.classes[ci]
            if not cls.pods:
                continue
            trial_req = req_vec.copy()
            for _ in range(k):
                trial_req += prep.class_requests64q[ci]
            trial_z = zmask & cm.mask[ci, prep.zone_kid, :Z]
            trial_ct = ctmask & cm.mask[ci, prep.ct_kid, :CT]
            fits = (trial_req[None, :] <= prep.it_alloc64q).all(axis=1)
            off_ok = (
                prep.off_avail_np
                & trial_z[None, :, None]
                & trial_ct[None, None, :]
            ).any(axis=(1, 2))
            trial = mask & prep.class_it[ci] & fits & off_ok
            if not trial.any():
                continue  # caller diverges this class (not in committed)
            mask, req_vec, zmask, ctmask = trial, trial_req, trial_z, trial_ct
            requests = resutil.merge_repeated(
                requests, resutil.requests_for_pods(cls.pods[0]), k
            )
            committed_counts.append((ci, k))

        remaining: list = []
        reqs_proto: list = []
        if committed_counts:
            options = [prep.catalog[i] for i in np.nonzero(mask[:T])[0]]
            joined = Requirements()
            joined.add(*(r.copy() for r in template.requirements.values()))
            for ci, _k in committed_counts:
                reqs = prep.classes[ci].requirements
                reqs_proto.extend(reqs.values())
                joined.add(*(r.copy() for r in reqs.values()))
            remaining = filter_instance_types(options, joined, {}).remaining
            if not remaining:
                # jointly-incompatible composition: everything diverges
                committed_counts = []
                reqs_proto = []
        return committed_counts, remaining, requests, reqs_proto

    def _host_fallback_add(
        self,
        pod: Pod,
        claims: List[InFlightNodeClaim],
        existing_sims: List[ExistingNodeSim],
        topo: Topology,
        pod_requests: Optional[dict] = None,
    ) -> Optional[str]:
        """Host placement via the shared greedy policy (place_pod), with the
        pools' remaining limits so fallback claims respect NodePool limits
        exactly like the greedy path (scheduler.go:417-434)."""
        if pod_requests is None:
            pod_requests = resutil.requests_for_pods(pod)
        return place_pod(
            pod,
            pod_requests,
            existing_sims,
            claims,
            self.templates,
            {id(t): o for t, o in zip(self.templates, self.daemon_overhead)},
            topo,
            getattr(self, "_round_remaining", {}),
        )
