"""The TPU provisioning solver — flagship model.

Drop-in counterpart of the greedy host scheduler
(controllers/provisioning/scheduling/scheduler.py): same inputs (nodepools,
instance-type catalog, existing nodes, pending pods), same Results shape,
but the FFD loop runs on device as a class-batched scan (ops/ffd.py) after
feasibility is precomputed as batched matmuls (ops/masks.py).

Pipeline per solve:
 1. host: pods → equivalence classes, sorted cpu/memory-descending
    (queue.go:76-112 ordering, lifted to classes)
 2. host: snapshot encode over a closed-world vocab (solver/snapshot.py)
 3. device: class×IT / class×template compatibility + fresh-node viability
 4. device: FFD scan over classes → per-slot take counts
 5. host: decode — merge each slot's class groups through the exact host
    algebra (Requirements.add + filter_instance_types), yielding the same
    InFlightNodeClaim objects the greedy path produces
 6. host: relaxation outer loop re-runs 1-5 for still-unschedulable pods
    (preferences.go:38-57)

NodePool resource limits are enforced exactly at claim-creation time
(provision() drops over-limit claims and errors their pods — no silent
livelock); the device solve itself does not model limits because a
per-pool budget cannot spill a class across templates the way the greedy
loop does (place_pod tries the next template when one pool's limit is
exhausted), and a budget without spill falsely errors schedulable pods.
The host-fallback path passes the pool's remaining resources through, so
fallback placements respect limits exactly like greedy.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodepool import NodePool
from karpenter_core_tpu.api.objects import Pod, Taint
from karpenter_core_tpu.cloudprovider.types import InstanceType
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
    ExistingNodeSim,
    IncompatibleError,
    InFlightNodeClaim,
    SimNode,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.nodeclaimtemplate import (
    NodeClaimTemplate,
    filter_instance_types,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.preferences import (
    Preferences,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.queue import (
    by_cpu_and_memory_descending,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
    Results,
    _daemon_compatible,
    node_daemon_pods,
    place_pod,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
    TYPE_ANTI_AFFINITY,
    TYPE_SPREAD,
    Topology,
    domain_universe,
    has_topology_constraints,
)
from karpenter_core_tpu.ops import masks as mops
from karpenter_core_tpu.ops import topoplan
from karpenter_core_tpu.ops.ffd import (
    BIG,
    RANK_NONE,
    ClassStep,
    FFDStatics,
    SlotState,
    ffd_solve,
)
from karpenter_core_tpu.scheduling import Requirement, Requirements, Taints
from karpenter_core_tpu.solver.snapshot import PodClass, group_pods
from karpenter_core_tpu.solver.vocab import (
    EntityMasks,
    GT_NONE,
    LT_NONE,
    decode_requirements,
)
from karpenter_core_tpu.utils import resources as resutil


# Densification deferral knobs (see _decode_topo): fresh topology slots at
# or below DENSIFY_THRESHOLD x median pod count drain through the host
# repair path, capped at DENSIFY_CAP of the fresh slots AND at
# DENSIFY_POD_BUDGET total pods per solve (the repair is ~ms/pod of host
# algebra, so the budget bounds the decode-time cost at any scale).
# Deliberately conservative: the pass exists to recover genuinely sparse
# tail slots. Uniform thinness (every slot near the median, the cfg3-5k
# +5% equilibrium of class-batched packing) is NOT repairable this way —
# sweeping thresholds showed median-wide deferral either re-creates the
# same slots (spread/anti constraints force fresh hosts) or devolves into
# a full host re-solve at ~ms/pod.
DENSIFY_THRESHOLD = 0.5
DENSIFY_CAP = 0.125
DENSIFY_POD_BUDGET = 256


def _neutralize(masks: EntityMasks) -> EntityMasks:
    """Apply the neutral-where-undefined invariant required by ffd_step."""
    d = masks.defines
    return EntityMasks(
        mask=np.where(d[:, :, None], masks.mask, True),
        defines=d,
        concrete=np.where(d, masks.concrete, False),
        negative=np.where(d, masks.negative, True),
        gt=masks.gt,
        lt=masks.lt,
    )


def _tolerates_taints(tolerations, taints) -> bool:
    return all(any(tol.tolerates(t) for tol in tolerations) for t in taints)


def _bucket(n: int, lo: int = 8) -> int:
    """Next power of two (>= lo): device-array axes pad to bucketed sizes so
    repeated solves with drifting shapes (class counts, vocab growth, pod
    mixes) hit the jit cache instead of recompiling for seconds."""
    return max(lo, 1 << max(n - 1, 1).bit_length())


def _pad(a: np.ndarray, targets: dict, fill) -> np.ndarray:
    """Pad axes of a to targets {axis: size} with a constant fill."""
    widths = [(0, 0)] * a.ndim
    for axis, size in targets.items():
        widths[axis] = (0, max(size - a.shape[axis], 0))
    if all(w == (0, 0) for w in widths):
        return a
    return np.pad(a, widths, constant_values=fill)


class _SlotOverflow(Exception):
    """More slots needed than max_slots — caller doubles and retries."""


# one slot per pod is the true worst case; 1M slots is far past any
# realistic solve and bounds the doubling loop
_SLOT_HARD_CAP = 1 << 20


@dataclass
class _Prepared:
    vocab: object
    resource_names: List[str]
    catalog: List[InstanceType]
    class_masks: EntityMasks
    class_requests: np.ndarray  # [C, R]
    classes: List[PodClass]
    templates: List[NodeClaimTemplate]
    # DEVICE-RESIDENT until the post-scan fetch (jax.Array at BUCKETED
    # shapes): class_it [Cp, Tp], tmpl_ok [Cp, Sp], new_template/kstar [Cp]
    # (ops/masks.fresh_viability outputs). _solve_once swaps class_it for
    # the fetched numpy [Cp, T] right before decode — the only host reader.
    class_it: object
    tmpl_ok: object
    new_template: object
    kstar: object
    statics: FFDStatics
    init_state: SlotState
    exist_taint_ok: np.ndarray  # [C, N]
    existing_sims: List[ExistingNodeSim]
    n_slots: int
    topo: Topology
    plan: topoplan.TopoPlan
    smask: np.ndarray  # [C, K, V] strict (pod_domains) value masks
    # float64 decode twins, quantized to the device's integer units
    # (unclamped — float64 is exact to 2^53): every decode refit runs in
    # the SAME arithmetic regime as the kernel, so slots the kernel packed
    # exactly full are never rejected over raw-float drift (repeated raw
    # adds drift ~1e-13 at exact boundaries — the r4 50k-topology decode
    # cliff, where whole slots deferred to the per-pod host path).
    # Ceil-requests/floor-capacity stays conservative vs true decimal
    # quantities (k8s resource.Quantity is fixed-point, resources.go:28-66).
    it_alloc64q: np.ndarray  # [pad_T, R] float64 (floor-quantized)
    class_requests64q: np.ndarray  # [C, R] float64 (ceil-quantized)
    tmpl_overhead64q: np.ndarray  # [pad_S, R] float64 (ceil-quantized)
    off_avail_np: np.ndarray  # [pad_T, Z, CT] bool
    tmpl_it_np: np.ndarray  # [pad_S, pad_T] bool
    tmpl_mask_np: np.ndarray  # [pad_S, K, V] bool
    zone_kid: int
    ct_kid: int
    n_zones: int
    n_cts: int
    level_iters: int = 32


class DeviceScheduler:
    """Same construction surface as the greedy Scheduler, device solve."""

    def __init__(
        self,
        nodepools: List[NodePool],
        instance_types: Dict[str, List[InstanceType]],
        existing_nodes: Optional[List[SimNode]] = None,
        daemonset_pods: Optional[List[Pod]] = None,
        max_slots: int = 256,
        topology: Optional[Topology] = None,
        unavailable_offerings: "frozenset | set" = frozenset(),
    ):
        # ICE'd offerings project onto the catalog exactly like the greedy
        # path (apply_unavailable), so the host-side machinery — template
        # prefilter, decode refit, host fallback, price ordering — all see
        # the stockout; the device side additionally masks the offerings
        # tensor (off_avail in _prepare_with_vocab) so in-kernel zone/ct
        # viability excludes the stocked-out rows
        from karpenter_core_tpu.cloudprovider.types import apply_unavailable

        instance_types = apply_unavailable(instance_types, unavailable_offerings)
        self.unavailable_offerings = frozenset(unavailable_offerings)
        # a supplied Topology carries cluster context (existing pods,
        # exclusions); its groups are rebuilt fresh each solve round, so only
        # the constructor inputs are kept
        self._topology_context = topology
        self.nodepools = sorted(nodepools, key=lambda n: (-n.spec.weight, n.name))
        self.instance_types = instance_types
        # initialized nodes first, then by name (scheduler.go:344-354) —
        # must match the greedy oracle's fill order
        self.existing_nodes = sorted(
            existing_nodes or [], key=lambda n: (not n.initialized, n.name)
        )
        self.daemonset_pods = list(daemonset_pods or [])
        self.max_slots = max_slots
        # NodePool limits minus existing usage (scheduler.go:85-88,336-340)
        self.remaining_resources: Dict[str, dict] = {
            np_.name: dict(np_.spec.limits)
            for np_ in self.nodepools
            if np_.spec.limits
        }
        for node in self.existing_nodes:
            if node.nodepool_name in self.remaining_resources:
                self.remaining_resources[node.nodepool_name] = resutil.subtract(
                    self.remaining_resources[node.nodepool_name],
                    node.capacity or node.available,
                )
        self.domains_universe = domain_universe(
            nodepools, instance_types, self.existing_nodes
        )

        tolerate_pns = any(
            t.effect == "PreferNoSchedule"
            for np_ in self.nodepools
            for t in np_.spec.template.taints
        )
        self.preferences = Preferences(tolerate_pns)

        self.templates: List[NodeClaimTemplate] = []
        for np_ in self.nodepools:
            nct = NodeClaimTemplate.from_nodepool(np_)
            nct.instance_type_options = filter_instance_types(
                instance_types.get(np_.name, []), nct.requirements, {}
            ).remaining
            if nct.instance_type_options:
                self.templates.append(nct)

        # daemon overhead per template (scheduler.go:358-364)
        self.daemon_overhead = [
            resutil.requests_for_pods(
                *[p for p in self.daemonset_pods if _daemon_compatible(nct, p)]
            )
            for nct in self.templates
        ]

    # ------------------------------------------------------------------

    def prewarm(self, class_buckets: Sequence[int] = (8, 64, 256)) -> None:
        """Compile (or load from the persistent compile cache) the FFD
        kernels for the common class-count buckets before the first real
        batch. Kernel shapes bucket on the class axis (_bucket), so a
        synthetic solve with N distinct pod shapes warms the same jit entry
        a real N-class batch hits; on a restarted operator with the on-disk
        XLA cache (utils/jaxenv.enable_persistent_compile_cache) this turns
        the first-batch compile cliff into a cache load (VERDICT r4 item 4).
        The jit cache is process-global — any DeviceScheduler instance
        warms every later one with the same catalog/pool shapes."""
        GIB = 2.0**30
        from karpenter_core_tpu.api.objects import ObjectMeta

        for target in class_buckets:
            pods = [
                Pod(
                    metadata=ObjectMeta(name=f"prewarm-{target}-{i}"),
                    resource_requests={
                        "cpu": 0.001 * (1 + i % 64),
                        "memory": 0.125 * GIB * (1 + i // 64),
                    },
                )
                for i in range(target)
            ]
            self.solve(pods)

    def solve(self, pods: List[Pod]) -> Results:
        """Device solve + host decode + relaxation outer loop.

        Each relaxation round re-solves the FULL pod set (relaxations mutate
        only previously-failed pods' specs), so placements from earlier rounds
        are never dropped — the same world-re-solve the reference reaches via
        requeue-on-relax (scheduler.go:251-258)."""
        all_pods = list(pods)
        errors: Dict[str, str] = {}
        claims: List[InFlightNodeClaim] = []
        # fresh per-solve copy: place_pod subtracts from it as fallback
        # claims open, and a reused scheduler must not accumulate rounds
        self._round_remaining = {
            k: dict(v) for k, v in self.remaining_resources.items()
        }
        existing_sims: List[ExistingNodeSim] = []
        max_slots = self.max_slots
        while max_slots < len(self.existing_nodes):
            max_slots *= 2

        from karpenter_core_tpu.metrics import wiring as m

        # relaxation terminates naturally: each relax() strips one soft term
        # (preferences.go:38-57); the greedy oracle loops the same way
        first_round = True
        while True:
            if not first_round:
                m.SOLVER_RELAX_ROUNDS.inc()
            first_round = False
            with m.SOLVER_SOLVE_DURATION.time():
                result = self._solve_once(all_pods, max_slots)
            if result is None:  # slot overflow — retry larger
                if max_slots >= _SLOT_HARD_CAP:
                    errors = {
                        p.uid: f"solver slot overflow at {max_slots} slots"
                        for p in all_pods
                    }
                    return Results(
                        new_node_claims=[], existing_nodes=[], pod_errors=errors
                    )
                max_slots *= 2
                continue
            claims, existing_sims, failed = result
            errors = {p.uid: msg for p, msg in failed}
            if not failed:
                break
            relaxed_any = False
            for p, _msg in failed:
                if self.preferences.relax(p):
                    relaxed_any = True
            if not relaxed_any:
                break

        for c in claims:
            c.finalize_scheduling()
        return Results(
            new_node_claims=claims,
            existing_nodes=existing_sims,
            pod_errors=errors,
        )

    # ------------------------------------------------------------------

    def _solve_once(
        self, pods: List[Pod], max_slots: int
    ) -> Optional[Tuple[List[InFlightNodeClaim], List[ExistingNodeSim], list]]:
        if not self.templates and not self.existing_nodes:
            # no viable templates and no existing capacity: everything fails
            return [], [], [(p, "no nodepool matched pod") for p in pods]

        # one Topology per solve round; every pod's groups are (re)built so
        # relaxed specs take effect (topology.go NewTopology:60-86)
        ctx = self._topology_context
        topo = Topology(
            domains={
                k: set(v)
                for k, v in (
                    ctx.domains if ctx is not None else self.domains_universe
                ).items()
            },
            existing_pods=ctx.existing_pods if ctx is not None else None,
            excluded_pod_uids=ctx.excluded_pods if ctx is not None else (),
        )
        topo.ensure_inverse_initialized()
        for p in pods:
            # constraint-free pods build no groups; skipping the call is the
            # 50k-path win (update() itself is a no-op for them)
            if p.topology_spread_constraints or p.affinity is not None:
                topo.update(p)

        # the topology planner decides which constraint shapes run in-kernel
        # (device count state) and which fall back to the host algebra
        classes = self._sorted_classes(pods, topo)
        plan = topoplan.plan_topology(classes, topo)
        self._composition_cache: Dict[tuple, tuple] = {}

        from karpenter_core_tpu.metrics import wiring as m

        try:
            with m.SOLVER_PREPARE_DURATION.time():
                prep = self._prepare_with_vocab(plan, max_slots, topo)
        except _SlotOverflow:
            return None

        kernel_timer = m.SOLVER_KERNEL_DURATION.time()
        kernel_timer.__enter__()
        state, takes, unplaced = ffd_solve(
            prep.init_state,
            self._class_steps(prep),
            prep.statics,
            level_iters=prep.level_iters,
        )
        # one device->host transfer for everything decode reads; the slot
        # planes ride along only when topology decode needs them
        fetch = dict(
            overflow=state.overflow,
            takes=takes,
            unplaced=unplaced,
            template=state.template,
            # decode reads class_it host-side (_decode_composition); it
            # rides the single post-scan fetch instead of its own sync
            class_it=prep.class_it,
        )
        if plan.has_device_topology():
            fetch.update(
                valmask=state.valmask,
                defines=state.defines,
                complement=state.complement,
                gt=state.gt,
                lt=state.lt,
                itmask=state.itmask,
                hcount=state.hcount,
                zcount=state.zcount,
            )
        out = jax.device_get(fetch)
        kernel_timer.__exit__(None, None, None)
        if bool(out["overflow"]):
            return None
        # slice bucketed device shapes back to the natural sizes decode
        # (and the topoplan arrays) index with
        J = len(plan.steps)
        sh = self._pad_shapes
        out["takes"] = np.asarray(out["takes"])[:J]
        out["unplaced"] = np.asarray(out["unplaced"])[:J]
        if plan.has_device_topology():
            out["valmask"] = np.asarray(out["valmask"])[:, : sh["K"], : sh["V"]]
            out["defines"] = np.asarray(out["defines"])[:, : sh["K"]]
            out["complement"] = np.asarray(out["complement"])[:, : sh["K"]]
            out["gt"] = np.asarray(out["gt"])[:, : sh["K"]]
            out["lt"] = np.asarray(out["lt"])[:, : sh["K"]]
            out["itmask"] = np.asarray(out["itmask"])[:, : sh["T"]]
            out["hcount"] = np.asarray(out["hcount"])[:, : sh["Gh"]]
            out["zcount"] = np.asarray(out["zcount"])[: sh["Gz"], : sh["V"]]
        prep.class_it = np.asarray(out["class_it"])[:, : sh["T"]]
        with m.SOLVER_DECODE_DURATION.time():
            claims, existing_sims, failed = self._decode(prep, out)

        # ineligible topology classes: host loop over the post-device cluster
        fallback_pods = [p for cls in plan.fallback_classes for p in cls.pods]
        if fallback_pods:
            m.SOLVER_HOST_FALLBACK_PODS.inc(
                {"cause": "ineligible"}, by=len(fallback_pods)
            )
        fallback_requests = {
            p.uid: resutil.requests_for_pods(p) for p in fallback_pods
        }
        for p in by_cpu_and_memory_descending(fallback_pods, fallback_requests):
            err = self._host_fallback_add(
                p, claims, existing_sims, topo, fallback_requests[p.uid]
            )
            if err is not None:
                failed.append((p, err))
        return claims, existing_sims, failed

    # ------------------------------------------------------------------

    def _sorted_classes(self, pods: List[Pod], topo: Topology) -> List[PodClass]:
        # labels/pod-affinity join the class key only when a topology group
        # could observe them (see _spec_signature)
        label_aware = bool(topo.topologies or topo.inverse_topologies)
        classes = group_pods(pods, label_aware=label_aware)
        # class order = pod queue order lifted to classes (queue.go:76-112)
        classes.sort(
            key=lambda c: (
                -c.requests.get("cpu", 0.0),
                -c.requests.get("memory", 0.0),
                min(p.metadata.creation_timestamp for p in c.pods),
            )
        )
        if label_aware:
            # Host-floor-first ordering — a deliberate, measured improvement
            # over the reference's pure size order (queue.go:76-112).
            # Hostname-keyed anti-affinity/spread classes need DISTINCT
            # hosts (min floats at zero while fresh nodes are creatable,
            # topologygroup.go:235-238): the slot floor they force is
            # max(per-group demand), independent of WHEN they run — but run
            # mid-scan (size order), early such classes find few existing
            # slots and open fresh ones the oracle's pod-interleaved walk
            # avoids. Running them FIRST establishes the host floor with
            # the minimum slot count, and the capacity-driven classes then
            # fill those slots instead of opening their own: the diverse
            # 5k topology mix drops 127 -> 91 nodes (greedy oracle: 121),
            # the 50k mix 314 -> 235 (greedy: 315). Stable within ranks,
            # so size order is preserved among peers.
            # Promote ONLY classes whose owned groups are exclusively
            # hostname anti-affinity/spread: a promoted class must not
            # depend on other classes' placements. A class that also owns a
            # pod-AFFINITY group (or any label-keyed group) placed ahead of
            # its target would find zero count>0 domains and fail pods the
            # size order places.
            def rank(cls: PodClass) -> int:
                owned = topo._owned.get(cls.pods[0].uid, ())
                if not owned:
                    return 2
                best = 2
                for g in owned:
                    if g.key != apilabels.LABEL_HOSTNAME:
                        return 2
                    if g.type == TYPE_ANTI_AFFINITY:
                        best = min(best, 0)
                    elif g.type == TYPE_SPREAD:
                        best = min(best, 1)
                    else:  # hostname-keyed affinity still depends on targets
                        return 2
                return best

            classes.sort(key=rank)
        return classes

    def _prepare(
        self, pods: List[Pod], max_slots: int, topo: Topology
    ) -> _Prepared:
        """Topology-free prepare entry for the consolidation sweep and the
        sharded-solver tests (callers guarantee no topology-coupled pods)."""
        plan = topoplan.plan_topology(self._sorted_classes(pods, topo), topo)
        return self._prepare_with_vocab(plan, max_slots, topo)

    def _prepare_with_vocab(
        self, plan: topoplan.TopoPlan, max_slots, topo: Topology
    ) -> _Prepared:
        from karpenter_core_tpu.solver.vocab import Vocab, encode_requirements_batch

        classes = plan.device_classes
        catalog = self._catalog_union()
        T, S = len(catalog), len(self.templates)
        # T == 0 (existing-capacity-only solve) keeps a dummy never-viable
        # IT axis so reductions over T stay well-formed; same for the
        # template axis S (gathers on a zero-size axis are invalid)
        pad_T = max(T, 1)
        pad_S = max(S, 1)
        exist_label_reqs = [
            Requirements.from_labels(n.labels) for n in self.existing_nodes
        ]

        vocab = Vocab()
        for cls in classes:
            vocab.observe_requirements(cls.requirements)
        for t in self.templates:
            vocab.observe_requirements(t.requirements)
        for r in exist_label_reqs:
            vocab.observe_requirements(r)
        for it in catalog:
            for off in it.offerings:
                vocab.observe_requirements(off.requirements)
        # Catalog instance types contribute VALUES only for keys some other
        # entity mentions. An 800-type catalog otherwise pushes V to 800 via
        # the instance-type name key and bloats every [N,K,V] slot plane;
        # instance-type narrowing rides the dedicated [N,T] itmask instead.
        # Exactness: keys only the catalog defines never meet a non-catalog
        # requirement in any shared-key comparison, and class/template-vs-IT
        # compat stays correct because an unobserved IT value yields an
        # all-false mask — empty intersection — exactly when the other side's
        # explicit values differ (closed-world argument in solver/vocab.py).
        mentioned = set(vocab.keys)
        for it in catalog:
            for key, req in it.requirements.items():
                vocab.key_id(key)
                if key in mentioned:
                    for v in req.values:
                        vocab.value_id(key, v)
        # topology-domain universe joins the closed world (the kernel's
        # admissibility masks index the label-group keys' value rows)
        topoplan.observe_domains(plan, vocab)
        frozen = vocab.finalize()
        topoplan.finalize_arrays(plan, frozen, topo)
        well_known = np.array(
            [k in apilabels.WELL_KNOWN_LABELS for k in frozen.key_names], dtype=bool
        )

        # resource axis
        resource_names = list(
            dict.fromkeys(
                ["cpu", "memory", "pods", "ephemeral-storage"]
                + [n for c in classes for n in c.requests]
                + [n for it in catalog for n in it.allocatable()]
                # daemon overhead joins every fresh claim's requests, so its
                # resource names must be on the axis or the vectorized fit
                # check would silently drop them
                + [n for o in self.daemon_overhead for n in o]
            )
        )
        R = len(resource_names)

        # Integer-unit quantization: the device planes hold integer-valued
        # float32 (milli-units for cpu and counts, Mi for memory-like
        # resources), so every in-kernel sum/difference/division is EXACT
        # below 2^24 and exact-boundary fits are neither rejected (the old
        # K_MARGIN shaved floor((alloc-req)/r) by one at exact fits, opening
        # a fresh node where the greedy oracle's float64 math packs the last
        # pod) nor spuriously accepted. Requests round UP, capacity rounds
        # DOWN — the device stays conservative at sub-unit granularity and
        # the float64 decode refit repairs any residual optimism.
        # cpu is the only fractional k8s resource (milli-granular); memory
        # and hugepages quantize to Mi (exact up to 2^24 Mi = 16 TiB per
        # slot sum), ephemeral-storage to Gi (NVMe-dense nodes reach tens
        # of TB; Gi keeps them far under 2^24); everything else (pods,
        # integral extended resources) keeps unit granularity so the 24-bit
        # exact-integer headroom isn't burned on a pointless inflation.
        _MI, _GI = 2.0**20, 2.0**30
        quant = np.array(
            [
                _GI
                if n == "ephemeral-storage"
                else _MI
                if n == "memory" or n.startswith("hugepages-")
                else 1e-3
                if n == "cpu"
                else 1.0
                for n in resource_names
            ],
            dtype=np.float64,
        )
        # the exactness invariant the margin-free kernel floor rests on:
        # quantized values
        # must stay integer-representable in float32. Clamping is the
        # enforcement — capacity clamps low (conservative), and a clamped
        # request exceeds every real node anyway; the float64 decode refit
        # repairs either direction.
        _QMAX = float(2**24 - 1)

        def _qraw(rl: dict) -> np.ndarray:
            raw = np.array(
                [rl.get(n, 0.0) for n in resource_names], dtype=np.float64
            )
            return raw / quant

        def rvec(rl: dict) -> np.ndarray:
            """Requests-side quantization (ceil)."""
            x = np.ceil(_qraw(rl) * (1.0 - 1e-12) - 1e-9)
            return np.minimum(x, _QMAX).astype(np.float32)

        def rvec_cap(rl: dict) -> np.ndarray:
            """Capacity-side quantization (floor)."""
            x = np.floor(_qraw(rl) * (1.0 + 1e-12) + 1e-9)
            return np.minimum(x, _QMAX).astype(np.float32)

        class_masks = _neutralize(
            encode_requirements_batch(frozen, [c.requirements for c in classes])
        )
        # strict (pod_domains) masks — what topology admissibility consults
        # (topology.go:166-188 passes strict reqs when preferences exist)
        from karpenter_core_tpu.scheduling.requirements import (
            has_preferred_node_affinity,
        )

        strict_enc = encode_requirements_batch(
            frozen,
            [
                c.strict_requirements
                if c.pods and has_preferred_node_affinity(c.pods[0])
                else c.requirements
                for c in classes
            ],
        )
        smask = np.where(
            strict_enc.defines[:, :, None], strict_enc.mask, True
        ) if len(classes) else np.ones((0, frozen.K, frozen.V), dtype=bool)
        it_masks = encode_requirements_batch(frozen, [it.requirements for it in catalog])
        tmpl_masks = _neutralize(
            encode_requirements_batch(frozen, [t.requirements for t in self.templates])
        )
        if S == 0:  # dummy neutral template row (never selected: tmpl_ok False)
            tmpl_masks = EntityMasks(
                mask=np.ones((pad_S, frozen.K, frozen.V), dtype=bool),
                defines=np.zeros((pad_S, frozen.K), dtype=bool),
                concrete=np.zeros((pad_S, frozen.K), dtype=bool),
                negative=np.ones((pad_S, frozen.K), dtype=bool),
                gt=np.full((pad_S, frozen.K), GT_NONE, dtype=np.int32),
                lt=np.full((pad_S, frozen.K), LT_NONE, dtype=np.int32),
            )
        exist_masks = (
            _neutralize(encode_requirements_batch(frozen, exist_label_reqs))
            if exist_label_reqs
            else None
        )

        C = len(classes)

        # dispatch the device compat kernels NOW and fetch after the host
        # loops below — jax dispatch is async, so the [C, T] intersect and
        # [C, S] compatible computes overlap the rvec/offering Python work
        # instead of blocking back-to-back.
        # class axis buckets before the jitted kernels, or a drifting class
        # count recompiles them every solve (the shape-churn cliff)
        cm, im, tm = class_masks, it_masks, tmpl_masks
        Cp = _bucket(C)

        def cpad(a, fill):
            return _pad(a, {0: Cp}, fill)

        cmask_p = np.where(
            cpad(cm.defines, False)[:, :, None], cpad(cm.mask, False), True
        )
        class_it_dev = mops.intersects(
            cmask_p, cpad(cm.defines, False), cpad(cm.concrete, False),
            cpad(cm.negative, True), cpad(cm.gt, GT_NONE),
            cpad(cm.lt, LT_NONE),
            im.mask, im.defines, im.concrete, im.negative, im.gt, im.lt,
        ) if C and T else None
        tmpl_compat_dev = mops.compatible(
            cmask_p, cpad(cm.defines, False), cpad(cm.concrete, False),
            cpad(cm.negative, True), cpad(cm.gt, GT_NONE),
            cpad(cm.lt, LT_NONE),
            tm.mask, tm.defines, tm.concrete, tm.negative, tm.gt, tm.lt,
            jnp.asarray(well_known),
        ) if C and S else None

        def rvec64q(rl: dict) -> np.ndarray:
            """Requests-side quantization, float64 (ceil, unclamped)."""
            return np.ceil(_qraw(rl) * (1.0 - 1e-12) - 1e-9)

        def rvec64q_cap(rl: dict) -> np.ndarray:
            """Capacity-side quantization, float64 (floor, unclamped)."""
            return np.floor(_qraw(rl) * (1.0 + 1e-12) + 1e-9)

        class_requests = np.stack(
            [rvec(resutil.requests_for_pods(c.pods[0])) for c in classes]
        ) if classes else np.zeros((0, R), dtype=np.float32)
        class_requests64q = np.stack(
            [rvec64q(resutil.requests_for_pods(c.pods[0])) for c in classes]
        ) if classes else np.zeros((0, R), dtype=np.float64)

        it_alloc = np.zeros((pad_T, R), dtype=np.float32)
        it_alloc64q = np.zeros((pad_T, R), dtype=np.float64)
        for ti, it in enumerate(catalog):
            it_alloc[ti] = rvec_cap(it.allocatable())
            it_alloc64q[ti] = rvec64q_cap(it.allocatable())

        # offerings tensor [T, Z, CT] over the zone/ct vocab rows
        zone_kid = frozen.keys.get(apilabels.LABEL_TOPOLOGY_ZONE, 0)
        ct_kid = frozen.keys.get(apilabels.CAPACITY_TYPE_LABEL_KEY, 0)
        Z = max(len(frozen.value_names[zone_kid]), 1)
        CT = max(len(frozen.value_names[ct_kid]), 1)
        off_avail = np.zeros((pad_T, Z, CT), dtype=bool)
        for ti, it in enumerate(catalog):
            for off in it.offerings:
                if not off.available:
                    continue
                # the unavailable-offerings tensor mask: ICE'd rows never
                # enter fresh-node viability (apply_unavailable already
                # flipped copies' available flags; this guards catalogs
                # handed in pre-built, e.g. over the sidecar wire)
                if off.key(it.name) in self.unavailable_offerings:
                    continue
                z = frozen.values[zone_kid].get(off.zone)
                c_ = frozen.values[ct_kid].get(off.capacity_type)
                if z is not None and c_ is not None:
                    off_avail[ti, z, c_] = True

        taint_ok = np.array(
            [
                [_tolerates_taints(c.tolerations, t.taints) for t in self.templates]
                for c in classes
            ],
            dtype=bool,
        ) if C and S else np.zeros((C, pad_S), dtype=bool)

        # template-IT viability from the host prefilter (exact reference path)
        it_index = {id(it): i for i, it in enumerate(catalog)}
        tmpl_it = np.zeros((pad_S, pad_T), dtype=bool)
        for si, t in enumerate(self.templates):
            for it in t.instance_type_options:
                tmpl_it[si, it_index[id(it)]] = True
        tmpl_overhead = np.stack(
            [rvec(o) for o in self.daemon_overhead]
        ) if S else np.zeros((pad_S, R), dtype=np.float32)
        tmpl_overhead64q = np.stack(
            [rvec64q(o) for o in self.daemon_overhead]
        ) if S else np.zeros((pad_S, R), dtype=np.float64)


        # initial slot state with existing nodes seeded in rows [0, E)
        N = max_slots
        K, V = frozen.K, frozen.V
        E = len(self.existing_nodes)
        if E > N:
            raise _SlotOverflow()

        valmask = np.ones((N, K, V), dtype=bool)
        defines = np.zeros((N, K), dtype=bool)
        complement = np.ones((N, K), dtype=bool)
        negative = np.ones((N, K), dtype=bool)
        gt = np.full((N, K), GT_NONE, dtype=np.int32)
        lt = np.full((N, K), LT_NONE, dtype=np.int32)
        itmask = np.zeros((N, pad_T), dtype=bool)
        requests = np.zeros((N, R), dtype=np.float32)
        capacity = np.full((N, R), np.float32(BIG))
        kind = np.zeros((N,), dtype=np.int8)
        template_arr = np.full((N,), -1, dtype=np.int32)

        existing_sims = []
        for ei, node in enumerate(self.existing_nodes):
            sim = ExistingNodeSim(node, topo, self._node_daemon_overhead(node))
            existing_sims.append(sim)
            valmask[ei] = exist_masks.mask[ei]
            defines[ei] = exist_masks.defines[ei]
            complement[ei] = np.where(
                exist_masks.defines[ei], ~exist_masks.concrete[ei], True
            )
            negative[ei] = np.where(
                exist_masks.defines[ei], exist_masks.negative[ei], True
            )
            gt[ei] = exist_masks.gt[ei]
            lt[ei] = exist_masks.lt[ei]
            requests[ei] = rvec(sim.requests)
            capacity[ei] = rvec_cap(sim.cached_available)
            kind[ei] = 1

        exist_taint_ok = np.ones((C, N), dtype=bool)
        for ci, cls in enumerate(classes):
            for ei, node in enumerate(self.existing_nodes):
                exist_taint_ok[ci, ei] = _tolerates_taints(
                    cls.tolerations, node.taints
                )

        # topology count state: hostname-group counts seeded per existing
        # slot; positive counts on non-slot hostnames only matter for the
        # affinity bootstrap check (h_possel0)
        slot_names = [n.name for n in self.existing_nodes]
        hcount0 = topoplan.initial_hcounts(plan, slot_names, N).T  # [N, Gh]
        slot_name_set = set(slot_names)
        h_possel0 = np.zeros((plan.Gh,), dtype=bool)
        for gi, dg in enumerate(plan.host_groups):
            h_possel0[gi] = any(
                cnt > 0
                for name, cnt in dg.group.domains.items()
                if name not in slot_name_set
            )

        # -- shape bucketing (the jit-cache / compile-cliff defense) --------
        # Padded entities are inert by construction: keys/values pad to the
        # neutral invariant (all-True slot valmask, False class/template
        # masks under defines=False), instance types/templates pad
        # never-viable, topology groups pad owner/sel=False, resources pad
        # zero-request. The kernel runs at padded shapes; _solve_once slices
        # outputs back to natural sizes before decode.
        Kp = _bucket(K)
        Vp = _bucket(V)
        Tp = _bucket(pad_T)
        Sp = _bucket(pad_S, lo=2)
        Rp = _bucket(R, lo=4)
        Ghp = _bucket(plan.Gh, lo=1)
        Gzp = _bucket(plan.Gz, lo=1)
        self._pad_shapes = dict(K=K, V=V, T=pad_T, Gh=plan.Gh, Gz=plan.Gz)

        def pad_masks(mask, defines_, concrete_like_complement, negative_,
                      gt_, lt_):
            """Pad one entity-mask family: V/K axes of the value mask pad
            False then re-neutralize where defines is False."""
            m2 = _pad(mask, {mask.ndim - 2: Kp, mask.ndim - 1: Vp}, False)
            d2 = _pad(defines_, {defines_.ndim - 1: Kp}, False)
            m2 = np.where(d2[..., None], m2, True)
            c2 = _pad(concrete_like_complement,
                      {concrete_like_complement.ndim - 1: Kp}, True)
            n2 = _pad(negative_, {negative_.ndim - 1: Kp}, True)
            g2 = _pad(gt_, {gt_.ndim - 1: Kp}, GT_NONE)
            l2 = _pad(lt_, {lt_.ndim - 1: Kp}, LT_NONE)
            return m2, d2, c2, n2, g2, l2

        tm_mask, tm_def, tm_comp, tm_neg, tm_gt, tm_lt = pad_masks(
            tmpl_masks.mask,
            tmpl_masks.defines,
            np.where(tmpl_masks.defines, ~tmpl_masks.concrete, True),
            np.where(tmpl_masks.defines, tmpl_masks.negative, True),
            tmpl_masks.gt,
            tmpl_masks.lt,
        )
        statics = FFDStatics(
            it_alloc=jnp.asarray(_pad(it_alloc, {0: Tp, 1: Rp}, 0.0)),
            off_avail=jnp.asarray(_pad(off_avail, {0: Tp}, False)),
            zone_key=jnp.int32(zone_kid),
            ct_key=jnp.int32(ct_kid),
            tmpl_mask=jnp.asarray(_pad(tm_mask, {0: Sp}, True)),
            tmpl_defines=jnp.asarray(_pad(tm_def, {0: Sp}, False)),
            tmpl_complement=jnp.asarray(_pad(tm_comp, {0: Sp}, True)),
            tmpl_negative=jnp.asarray(_pad(tm_neg, {0: Sp}, True)),
            tmpl_gt=jnp.asarray(_pad(tm_gt, {0: Sp}, GT_NONE)),
            tmpl_lt=jnp.asarray(_pad(tm_lt, {0: Sp}, LT_NONE)),
            tmpl_it=jnp.asarray(_pad(tmpl_it, {0: Sp, 1: Tp}, False)),
            tmpl_overhead=jnp.asarray(_pad(tmpl_overhead, {0: Sp, 1: Rp}, 0.0)),
            well_known=jnp.asarray(_pad(well_known, {0: Kp}, False)),
            gt_none=jnp.int32(GT_NONE),
            lt_none=jnp.int32(LT_NONE),
            h_type=jnp.asarray(_pad(plan.h_type, {0: Ghp}, 0)),
            h_skew=jnp.asarray(_pad(plan.h_skew, {0: Ghp}, 0)),
            h_possel0=jnp.asarray(_pad(h_possel0, {0: Ghp}, False)),
            z_type=jnp.asarray(_pad(plan.z_type, {0: Gzp}, 0)),
            z_skew=jnp.asarray(_pad(plan.z_skew, {0: Gzp}, 0)),
            z_key=jnp.asarray(_pad(plan.z_key, {0: Gzp}, 0)),
            z_mindom=jnp.asarray(
                _pad(plan.z_mindom, {0: Gzp}, topoplan.NO_MIN_DOMAINS)
            ),
            z_domains=jnp.asarray(_pad(plan.z_domains, {0: Gzp, 1: Vp}, False)),
            z_rank=jnp.asarray(_pad(plan.z_rank, {0: Gzp, 1: Vp}, RANK_NONE)),
        )

        # Fresh-node viability + kstar per class, ON DEVICE (ops/masks
        # fresh_viability) over the statics' BUCKETED arrays, so drifting
        # template/catalog/resource counts reuse the jit entry like every
        # other kernel: the compat results never detour through the host,
        # and the solve's only device sync is the post-scan output fetch
        # (class_it rides along in it for the decode). Dead-on equal to the
        # retired host loop: same quantized float32 floor arithmetic,
        # first-template-wins (pad rows carry tmpl_ok False and can never
        # be chosen).
        if C and S and T:
            class_it_b = jnp.pad(
                class_it_dev,
                ((0, 0), (0, Tp - class_it_dev.shape[1])),
            ) if class_it_dev.shape[1] < Tp else class_it_dev
            tmpl_ok_b = jnp.asarray(
                _pad(taint_ok, {0: Cp, 1: Sp}, False)
            ) & jnp.pad(
                tmpl_compat_dev,
                ((0, 0), (0, Sp - tmpl_compat_dev.shape[1])),
            )
            new_template, kstar = mops.fresh_viability(
                class_it_b,
                tmpl_ok_b,
                statics.tmpl_it,
                jnp.asarray(cpad(class_masks.mask[:, zone_kid, :Z], False)),
                jnp.asarray(cpad(class_masks.mask[:, ct_kid, :CT], False)),
                jnp.asarray(
                    _pad(tmpl_masks.mask[:, zone_kid, :Z], {0: Sp}, False)
                ),
                jnp.asarray(
                    _pad(tmpl_masks.mask[:, ct_kid, :CT], {0: Sp}, False)
                ),
                statics.off_avail,
                statics.it_alloc,
                statics.tmpl_overhead,
                jnp.asarray(cpad(_pad(class_requests, {1: Rp}, 0.0), 0.0)),
            )
            class_it = class_it_b  # [Cp, Tp] device-resident
            tmpl_ok = tmpl_ok_b  # [Cp, Sp] device-resident
        else:
            class_it = jnp.zeros((Cp, Tp), dtype=bool)
            tmpl_ok = jnp.zeros((Cp, Sp), dtype=bool)
            new_template = jnp.full((Cp,), -1, dtype=jnp.int32)
            kstar = jnp.zeros((Cp,), dtype=jnp.int32)
        # slot valmask pads True everywhere: defined keys re-acquire False
        # pad columns on first intersection with a (False-padded) class mask;
        # EXISTING slots' defined keys must pad False now or anti-affinity
        # rowcounts see phantom values
        valmask_p = _pad(valmask, {1: Kp, 2: Vp}, True)
        defines_p = _pad(defines, {1: Kp}, False)
        valmask_p[:, : K] = np.where(
            defines[:, :K, None],
            _pad(valmask, {2: Vp}, False)[:, :K],
            valmask_p[:, :K],
        )
        init_state = SlotState(
            valmask=jnp.asarray(valmask_p),
            defines=jnp.asarray(defines_p),
            complement=jnp.asarray(_pad(complement, {1: Kp}, True)),
            negative=jnp.asarray(_pad(negative, {1: Kp}, True)),
            gt=jnp.asarray(_pad(gt, {1: Kp}, GT_NONE)),
            lt=jnp.asarray(_pad(lt, {1: Kp}, LT_NONE)),
            itmask=jnp.asarray(_pad(itmask, {1: Tp}, False)),
            requests=jnp.asarray(_pad(requests, {1: Rp}, 0.0)),
            capacity=jnp.asarray(_pad(capacity, {1: Rp}, np.float32(BIG))),
            kind=jnp.asarray(kind),
            template=jnp.asarray(template_arr),
            podcount=jnp.zeros((N,), dtype=jnp.int32),
            next_free=jnp.int32(E),
            overflow=jnp.asarray(False),
            hcount=jnp.asarray(_pad(hcount0, {1: Ghp}, 0)),
            zcount=jnp.asarray(_pad(plan.zcount0, {0: Gzp, 1: Vp}, 0)),
            carry=jnp.int32(0),
        )

        # level-search iterations: the water level is bounded by seeded
        # topology counts + pods in this solve
        import math

        count_bound = 2 * (
            sum(c.count for c in classes)
            + (int(plan.zcount0.max()) if plan.zcount0.size else 0)
            + (int(hcount0.max()) if hcount0.size else 0)
            + 2
        )
        # bucket to a multiple of 4 so drifting pod counts share jit cache
        level_iters = -(-max(math.ceil(math.log2(count_bound)), 4) // 4) * 4

        return _Prepared(
            vocab=frozen,
            resource_names=resource_names,
            catalog=catalog,
            class_masks=class_masks,
            class_requests=class_requests,
            classes=classes,
            templates=self.templates,
            class_it=class_it,
            tmpl_ok=tmpl_ok,
            new_template=new_template,
            kstar=kstar,
            statics=statics,
            init_state=init_state,
            exist_taint_ok=exist_taint_ok,
            existing_sims=existing_sims,
            n_slots=N,
            topo=topo,
            plan=plan,
            smask=smask,
            it_alloc64q=it_alloc64q,
            class_requests64q=class_requests64q,
            tmpl_overhead64q=tmpl_overhead64q,
            off_avail_np=off_avail,
            tmpl_it_np=tmpl_it,
            tmpl_mask_np=tmpl_masks.mask,
            zone_kid=zone_kid,
            ct_kid=ct_kid,
            n_zones=Z,
            n_cts=CT,
            level_iters=level_iters,
        )

    def _class_steps(self, prep: _Prepared) -> ClassStep:
        """Per-STEP scanned arrays: one step per class, except self-selecting
        label-spread classes which expand to one pinned sub-step per
        admissible domain (ops/topoplan.py). All axes pad to the bucketed
        shapes of prep.statics/init_state; steps pad to a bucketed count
        with inert entries (count=0, no viable template — the scan carries
        state through them unchanged)."""
        cm = prep.class_masks
        plan = prep.plan
        steps = plan.steps
        V = prep.vocab.V
        cis = np.array([s.class_idx for s in steps], dtype=np.int32)
        counts = np.array(
            [prep.classes[ci].count for ci in cis], dtype=np.int32
        )
        J = len(steps)
        Jp = _bucket(J)
        Kp = int(prep.statics.well_known.shape[0])
        Vp = int(prep.statics.z_domains.shape[1])
        Tp = int(prep.statics.it_alloc.shape[0])
        Sp = int(prep.statics.tmpl_it.shape[0])
        Rp = int(prep.statics.it_alloc.shape[1])
        Ghp = int(prep.statics.h_type.shape[0])
        Gzp = int(prep.statics.z_type.shape[0])
        zone_rest = (
            np.stack(
                [
                    s.zone_rest
                    if s.zone_rest is not None
                    else np.zeros((V,), dtype=bool)
                    for s in steps
                ]
            )
            if J
            else np.zeros((0, V), dtype=bool)
        )

        def stepvec(values, dtype, fill):
            return _pad(np.array(values, dtype=dtype), {0: Jp}, fill)

        # device-resident per-class arrays (class_it/tmpl_ok/new_template/
        # kstar live on device, see _prepare_with_vocab): gather by padded
        # step index, pad the natural T/S axes up to the statics' bucketed
        # shapes, and neutralize the pad rows so inert steps stay inert
        ci_padded = np.zeros((Jp,), dtype=np.int32)
        ci_padded[:J] = cis
        ci_j = jnp.asarray(ci_padded)
        valid_j = jnp.asarray(np.arange(Jp) < J)
        class_it_g = prep.class_it[ci_j]
        if class_it_g.shape[1] < Tp:
            class_it_g = jnp.pad(
                class_it_g, ((0, 0), (0, Tp - class_it_g.shape[1]))
            )
        tmpl_ok_g = prep.tmpl_ok[ci_j]
        if tmpl_ok_g.shape[1] < Sp:
            tmpl_ok_g = jnp.pad(
                tmpl_ok_g, ((0, 0), (0, Sp - tmpl_ok_g.shape[1]))
            )

        mask = _pad(cm.mask[cis], {0: Jp, 1: Kp, 2: Vp}, False)
        defines = _pad(cm.defines[cis], {0: Jp, 1: Kp}, False)
        mask = np.where(defines[:, :, None], mask, True)  # neutral pads
        smask = _pad(prep.smask[cis], {0: Jp, 1: Kp, 2: Vp}, True)
        return ClassStep(
            mask=jnp.asarray(mask),
            defines=jnp.asarray(defines),
            concrete=jnp.asarray(_pad(cm.concrete[cis], {0: Jp, 1: Kp}, False)),
            negative=jnp.asarray(_pad(cm.negative[cis], {0: Jp, 1: Kp}, True)),
            gt=jnp.asarray(_pad(cm.gt[cis], {0: Jp, 1: Kp}, GT_NONE)),
            lt=jnp.asarray(_pad(cm.lt[cis], {0: Jp, 1: Kp}, LT_NONE)),
            count=jnp.asarray(_pad(counts, {0: Jp}, 0)),
            requests=jnp.asarray(
                _pad(prep.class_requests[cis], {0: Jp, 1: Rp}, 0.0)
            ),
            class_it=jnp.where(valid_j[:, None], class_it_g, False),
            tmpl_ok=jnp.where(valid_j[:, None], tmpl_ok_g, False),
            exist_taint_ok=jnp.asarray(
                _pad(prep.exist_taint_ok[cis], {0: Jp}, False)
            ),
            new_template=jnp.where(valid_j, prep.new_template[ci_j], -1),
            kstar=jnp.where(valid_j, prep.kstar[ci_j], 0),
            smask=jnp.asarray(smask),
            h_sel=jnp.asarray(_pad(plan.h_sel[cis], {0: Jp, 1: Ghp}, False)),
            h_owner=jnp.asarray(_pad(plan.h_owner[cis], {0: Jp, 1: Ghp}, False)),
            z_sel=jnp.asarray(_pad(plan.z_sel[cis], {0: Jp, 1: Gzp}, False)),
            z_owner=jnp.asarray(_pad(plan.z_owner[cis], {0: Jp, 1: Gzp}, False)),
            sub_value=jnp.asarray(
                stepvec([s.sub_value for s in steps], np.int32, -1)
            ),
            sub_first=jnp.asarray(
                stepvec([s.sub_first for s in steps], bool, True)
            ),
            sub_last=jnp.asarray(
                stepvec([s.sub_last for s in steps], bool, True)
            ),
            wf_group=jnp.asarray(
                stepvec([s.wf_group for s in steps], np.int32, -1)
            ),
            wf_key=jnp.asarray(
                stepvec([s.wf_key for s in steps], np.int32, -1)
            ),
            zone_rest=jnp.asarray(_pad(zone_rest, {0: Jp, 1: Vp}, False)),
        )

    def _catalog_union(self) -> List[InstanceType]:
        seen = {}
        for t in self.templates:
            for it in t.instance_type_options:
                seen.setdefault(id(it), it)
        # include full per-pool catalogs so class_it covers everything
        for its in self.instance_types.values():
            for it in its:
                seen.setdefault(id(it), it)
        return list(seen.values())

    def _node_daemon_overhead(self, node: SimNode) -> dict:
        return resutil.requests_for_pods(
            *node_daemon_pods(node, self.daemonset_pods)
        )

    # ------------------------------------------------------------------

    def _decode(
        self, prep: _Prepared, out: Dict[str, np.ndarray]
    ) -> Tuple[List[InFlightNodeClaim], List[ExistingNodeSim], list]:
        """Re-materialize device placements through the host algebra.

        Topology-free solves merge each slot's class groups with the exact
        reference-semantics machinery (Requirements.add +
        filter_instance_types). Topology solves instead reconstruct each
        fresh slot's joined requirements straight from the final device
        planes (decode_requirements — the planes already carry every
        admissibility tightening the kernel applied) and sync the host
        groups' domain counters from the device count state. Either way, any
        placement the host-side checks reject is re-placed through the host
        greedy add; only pods the host path also rejects surface as failures
        (and re-enter via relaxation)."""
        takes = np.asarray(out["takes"])
        unplaced = np.asarray(out["unplaced"])
        slot_template = np.asarray(out["template"])
        plan = prep.plan
        steps = plan.steps
        C = len(prep.classes)
        J = takes.shape[0] if takes.size else 0
        E = len(prep.existing_sims)
        failed: list = []
        divergent: List[Pod] = []

        # merge sub-steps per (slot, class) — pods of a class are
        # interchangeable — and collect per-class unplaced tails
        assigned: Dict[int, Dict[int, int]] = {}
        unplaced_by_class = np.zeros((C,), dtype=np.int64)
        for j in range(J):
            ci = steps[j].class_idx
            unplaced_by_class[ci] += int(unplaced[j])
            for n in np.nonzero(takes[j])[0]:
                slot = assigned.setdefault(int(n), {})
                slot[ci] = slot.get(ci, 0) + int(takes[j, int(n)])
        for ci, cls in enumerate(prep.classes):
            k_unplaced = int(unplaced_by_class[ci])
            if k_unplaced:
                for p in cls.pods[cls.count - k_unplaced :]:
                    failed.append((p, "no nodepool matched pod"))

        claims: List[InFlightNodeClaim] = []
        topo = prep.topo
        pod_cursor = {ci: 0 for ci in range(C)}

        if plan.has_device_topology():
            return self._decode_topo(
                prep, out, assigned, slot_template, pod_cursor, claims, failed
            )

        # ---- topology-free path ------------------------------------------
        # group-add is exact only when no topology group could observe these
        # pods (decode sees topology-free pods, but inverse anti-affinity
        # groups from the cluster can still select them by label)
        can_group = not topo.topologies and not topo.inverse_topologies

        for n in sorted(assigned):
            groups = sorted(assigned[n].items())
            if n < E:
                target = prep.existing_sims[n]
            else:
                si = int(slot_template[n])
                template = prep.templates[si]
                if can_group and self._decode_fresh_vectorized(
                    prep, si, template, groups, pod_cursor, topo,
                    claims, divergent,
                ):
                    continue
                target = InFlightNodeClaim(
                    template,
                    topo,
                    self.daemon_overhead[si],
                    template.instance_type_options,
                )
                claims.append(target)
            for ci, k in groups:
                cls = prep.classes[ci]
                start = pod_cursor[ci]
                pods = cls.pods[start : start + k]
                pod_cursor[ci] = start + k
                if not pods:
                    continue
                req = resutil.requests_for_pods(pods[0])
                if can_group and not pods[0].host_ports:
                    try:
                        target.add_group(pods, req)
                        continue
                    except IncompatibleError:
                        pass  # re-place pod-by-pod below
                for p in pods:
                    try:
                        target.add(p, req)
                    except IncompatibleError:
                        divergent.append(p)
        if divergent:
            from karpenter_core_tpu.metrics import wiring as m

            m.SOLVER_HOST_FALLBACK_PODS.inc(
                {"cause": "divergent"}, by=len(divergent)
            )
        for p in divergent:
            err = self._host_fallback_add(p, claims, prep.existing_sims, topo)
            if err is not None:
                failed.append((p, err))
        # drop empty claims (all groups failed), releasing their placeholder
        # hostnames from the shared per-round topology (see below)
        kept = []
        for c in claims:
            if c.pods:
                kept.append(c)
            else:
                c.destroy()
        if can_group:
            kept = self._repack_sparse_claims(kept)
        return kept, prep.existing_sims, failed

    def _repack_sparse_claims(
        self, claims: List[InFlightNodeClaim]
    ) -> List[InFlightNodeClaim]:
        """Eliminate class-batched tail fragmentation.

        The kernel opens ceil(rem/kstar) identical fresh slots per class
        (ops/ffd.py), which can strand a near-empty tail node the
        pod-at-a-time oracle never creates. Walk claims sparsest-first and
        try to re-place each one's pods into the other claims through the
        host algebra; a claim whose pods all move is dropped. Stops at the
        first claim that cannot fully drain (denser ones won't either).
        Topology-free solves only (the caller gates on can_group): moving a
        pod never touches domain counters here. A partial drain keeps the
        claim with its remaining pods — still a valid packing, requests
        intentionally left conservative (stale high) on the source."""
        if len(claims) < 2:
            return claims
        claims = sorted(claims, key=lambda c: len(c.pods))
        out = list(claims)
        for claim in claims:
            others = sorted(
                (c for c in out if c is not claim), key=lambda c: len(c.pods)
            )
            moved: List[Pod] = []
            ok = True
            for p in list(claim.pods):
                req = resutil.requests_for_pods(p)
                placed = False
                for o in others:
                    try:
                        o.add(p, req)
                        placed = True
                        break
                    except IncompatibleError:
                        continue
                if not placed:
                    ok = False
                    break
                moved.append(p)
            if not ok:
                # keep the claim with whatever didn't move; a moved pod
                # stays moved (both homes are valid, only one lists it)
                moved_ids = {id(p) for p in moved}
                claim.pods = [p for p in claim.pods if id(p) not in moved_ids]
                break
            claim.pods = []
            claim.destroy()
            out.remove(claim)
        return out

    # -- topology decode ---------------------------------------------------

    def _decode_topo(
        self,
        prep: _Prepared,
        out: Dict[str, np.ndarray],
        assigned: Dict[int, Dict[int, int]],
        slot_template: np.ndarray,
        pod_cursor: Dict[int, int],
        claims: List[InFlightNodeClaim],
        failed: list,
    ) -> Tuple[List[InFlightNodeClaim], List[ExistingNodeSim], list]:
        """Decode with device topology state: bulk commits, then host group
        count sync, then deferred per-pod replays.

        Ordering is load-bearing: deferred pods must replay through the host
        algebra AFTER the device counts (minus the deferred contributions)
        are synced into the host TopologyGroups, or they would place against
        stale counters."""
        plan, topo = prep.plan, prep.topo
        E = len(prep.existing_sims)
        valmask = np.asarray(out["valmask"])
        defines = np.asarray(out["defines"])
        complement = np.asarray(out["complement"])
        gt = np.asarray(out["gt"])
        lt = np.asarray(out["lt"])
        itmask = np.asarray(out["itmask"])
        hcount = np.asarray(out["hcount"]).astype(np.int64).copy()
        zcount = np.asarray(out["zcount"]).astype(np.int64).copy()

        deferred: List[Pod] = []
        densified = 0  # densify victims inside `deferred` (metrics split)
        # (slot, class, k, slot requirements, hostname) per bulk commit
        committed: List[tuple] = []
        slot_hostnames: Dict[int, str] = {}
        slot_claims: Dict[int, InFlightNodeClaim] = {}  # fresh slots only

        def defer(n: int, ci: int, pods: List[Pod]) -> None:
            self._topo_subtract(
                plan, valmask, defines, complement, n, ci, len(pods),
                hcount, zcount,
            )
            deferred.extend(pods)

        for n in sorted(assigned):
            groups = sorted(assigned[n].items())
            if n < E:
                target = prep.existing_sims[n]
                slot_hostnames[n] = target.name
                for ci, k in groups:
                    cls = prep.classes[ci]
                    start = pod_cursor[ci]
                    pods = cls.pods[start : start + k]
                    pod_cursor[ci] = start + k
                    if not pods:
                        continue
                    if pods[0].host_ports:
                        defer(n, ci, pods)
                        continue
                    try:
                        target.add_group(pods, resutil.requests_for_pods(pods[0]))
                        committed.append(
                            (n, ci, len(pods), target.requirements, target.name)
                        )
                    except IncompatibleError:
                        defer(n, ci, pods)
            else:
                self._commit_fresh_topo(
                    prep, n, int(slot_template[n]), groups, pod_cursor,
                    claims, committed, slot_hostnames, defer,
                    valmask, defines, complement, gt, lt, itmask,
                    slot_claims,
                )

        # Voluntary densification deferral (the topology twin of
        # _repack_sparse_claims): the class-batched kernel strands sparse
        # tail slots (ceil(rem/kstar) per class) the pod-at-a-time oracle
        # never opens. Drain the sparsest fresh slots through the existing
        # subtract-and-repair machinery — their pods re-place one-by-one
        # into the other claims' residual capacity via the host algebra,
        # re-opening an equivalent node only when nothing admits them, so
        # the pass can only densify.
        if len(slot_claims) >= 2:
            sizes = sorted(len(c.pods) for c in slot_claims.values())
            median = sizes[len(sizes) // 2]
            eligible = sorted(
                (
                    (n, c)
                    for n, c in slot_claims.items()
                    if len(c.pods) <= int(median * DENSIFY_THRESHOLD)
                ),
                key=lambda nc: len(nc[1].pods),
            )[: int(len(slot_claims) * DENSIFY_CAP)]
            victims = []
            pod_budget = DENSIFY_POD_BUDGET
            for n, c in eligible:
                if len(c.pods) > pod_budget:
                    break
                pod_budget -= len(c.pods)
                victims.append((n, c))
            if victims:
                from karpenter_core_tpu.metrics import wiring as m

                densified = sum(len(c.pods) for _, c in victims)
                m.SOLVER_HOST_FALLBACK_PODS.inc(
                    {"cause": "densify"}, by=densified
                )
            for n, claim in victims:
                for entry in [e for e in committed if e[0] == n]:
                    _n, ci, k, _reqs, _hn = entry
                    self._topo_subtract(
                        plan, valmask, defines, complement, n, ci, k,
                        hcount, zcount,
                    )
                    committed.remove(entry)
                deferred.extend(claim.pods)
                claim.pods = []
                claim.destroy()
                claims.remove(claim)
                slot_hostnames.pop(n, None)

        self._sync_topo_counts(prep, hcount, zcount, slot_hostnames)
        self._recount_host_only(prep, committed)

        if len(deferred) > densified:
            from karpenter_core_tpu.metrics import wiring as m

            m.SOLVER_HOST_FALLBACK_PODS.inc(
                {"cause": "deferred"}, by=len(deferred) - densified
            )
        for p in deferred:
            err = self._host_fallback_add(p, claims, prep.existing_sims, topo)
            if err is not None:
                failed.append((p, err))

        kept = []
        for c in claims:
            if c.pods:
                kept.append(c)
            else:
                c.destroy()
        return kept, prep.existing_sims, failed

    def _commit_fresh_topo(
        self,
        prep: _Prepared,
        n: int,
        si: int,
        groups: List[Tuple[int, int]],
        pod_cursor: Dict[int, int],
        claims: List[InFlightNodeClaim],
        committed: List[tuple],
        slot_hostnames: Dict[int, str],
        defer,
        valmask: np.ndarray,
        defines: np.ndarray,
        complement: np.ndarray,
        gt: np.ndarray,
        lt: np.ndarray,
        itmask: np.ndarray,
        slot_claims: Optional[Dict[int, InFlightNodeClaim]] = None,
    ) -> None:
        """Materialize one fresh topology slot from the final device planes:
        float64-refit the take against the slot's final viable instance
        types, rebuild the joined requirements with decode_requirements, and
        commit in bulk. minValues / hostPort shapes go per-pod instead."""
        template = prep.templates[si]
        T = len(prep.catalog)
        entries: List[Tuple[int, List[Pod]]] = []
        for ci, k in groups:
            cls = prep.classes[ci]
            start = pod_cursor[ci]
            pods = cls.pods[start : start + k]
            pod_cursor[ci] = start + k
            if pods:
                entries.append((ci, pods))
        if not entries:
            return
        plane_ok = not template.requirements.has_min_values() and all(
            not pods[0].host_ports
            and not prep.classes[ci].requirements.has_min_values()
            for ci, pods in entries
        )
        # quantized-integer refit (exact under repeated addition): the same
        # arithmetic regime as the device kernel, so a slot the kernel packed
        # exactly full is not deferred over a 1e-13 raw-float drift
        req_vec = prep.tmpl_overhead64q[si].copy()
        requests = dict(self.daemon_overhead[si])
        for ci, pods in entries:
            for _ in range(len(pods)):
                req_vec += prep.class_requests64q[ci]
            requests = resutil.merge_repeated(
                requests, resutil.requests_for_pods(pods[0]), len(pods)
            )
        opt_idx = [
            int(t)
            for t in np.nonzero(itmask[n, :T])[0]
            if np.all(req_vec <= prep.it_alloc64q[t])
        ]
        if not plane_ok or not opt_idx:
            for ci, pods in entries:
                defer(n, ci, pods)
            return
        claim = InFlightNodeClaim(
            template,
            prep.topo,
            self.daemon_overhead[si],
            [prep.catalog[t] for t in opt_idx],
        )
        reqs = decode_requirements(
            prep.vocab, valmask[n], defines[n], complement[n], gt[n], lt[n]
        )
        reqs.add(
            Requirement.new(apilabels.LABEL_HOSTNAME, "In", [claim.hostname])
        )
        claim.requirements = reqs
        claim.pods = [p for _, pods in entries for p in pods]
        claim.requests = requests
        claims.append(claim)
        slot_hostnames[n] = claim.hostname
        if slot_claims is not None:
            slot_claims[n] = claim
        for ci, pods in entries:
            committed.append((n, ci, len(pods), reqs, claim.hostname))

    @staticmethod
    def _topo_subtract(
        plan, valmask, defines, complement, n, ci, k, hcount, zcount
    ) -> None:
        """Remove a deferred placement's contributions from the device
        counts — the mirror of the kernel's count update, evaluated on the
        final planes (a slot pinned by a LATER class than the deferred one
        can over-subtract by at most the deferred pod count; deferred slots
        are divergence repairs, so the drift is bounded and rare)."""
        if plan.h_sel.size:
            hcount[n, :] -= k * plan.h_sel[ci].astype(np.int64)
        for gi in range(len(plan.label_groups)):
            if not plan.z_sel[ci, gi]:
                continue
            kid = int(plan.z_key[gi])
            if not defines[n, kid] or complement[n, kid]:
                continue
            row = valmask[n, kid]
            if plan.z_type[gi] == 1 or row.sum() == 1:
                zcount[gi] -= k * row.astype(np.int64)

    def _sync_topo_counts(
        self, prep: _Prepared, hcount, zcount, slot_hostnames: Dict[int, str]
    ) -> None:
        """Overwrite the host TopologyGroups' domain counters with the
        device truth (counts for untouched slots/domains are unchanged by
        construction, so only synced entries are written)."""
        plan = prep.plan
        for gi, dg in enumerate(plan.host_groups):
            g = dg.group
            for n, name in slot_hostnames.items():
                cnt = max(int(hcount[n, gi]), 0)
                if name not in g.domains and cnt == 0:
                    continue
                g.domains[name] = cnt
                if cnt > 0:
                    g.empty_domains.discard(name)
                else:
                    g.empty_domains.add(name)
        for gi, dg in enumerate(plan.label_groups):
            g = dg.group
            kid = int(plan.z_key[gi])
            names = prep.vocab.value_names[kid]
            # union with nonzero count columns: the kernel can record
            # placements on vocab values outside the registered universe (a
            # counted-not-constrained class pinned to an unregistered
            # domain); TopologyGroup.record creates new domain entries, so
            # the sync must too or host-fallback replays see stale counters
            cols = np.nonzero(plan.z_domains[gi] | (zcount[gi] != 0))[0]
            for vid in cols:
                name = names[vid]
                cnt = max(int(zcount[gi, vid]), 0)
                if name not in g.domains and cnt == 0:
                    continue
                g.domains[name] = cnt
                if cnt > 0:
                    g.empty_domains.discard(name)
                else:
                    g.empty_domains.add(name)

    def _recount_host_only(self, prep: _Prepared, committed: List[tuple]) -> None:
        """Groups the device could not model (non-trivial spread node
        filters) re-count the bulk-committed placements host-side at
        (class × slot) granularity — their owner classes always run on the
        host, so these counters only need the device classes' contributions."""
        plan = prep.plan
        if not plan.host_only_groups:
            return
        from karpenter_core_tpu.scheduling.requirements import (
            ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
        )

        for g in plan.host_only_groups:
            for n, ci, k, reqs, hostname in committed:
                rep = prep.classes[ci].pods[0]
                if not g.selects(rep):
                    continue
                if not g.node_filter.matches_requirements(
                    reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
                ):
                    continue
                if g.key == apilabels.LABEL_HOSTNAME:
                    domain = hostname
                else:
                    dom_req = reqs.get(g.key)
                    vals = dom_req.sorted_values()
                    if dom_req.complement or len(vals) != 1:
                        continue
                    domain = vals[0]
                g.record(*([domain] * k))

    def _decode_fresh_vectorized(
        self,
        prep: _Prepared,
        si: int,
        template,
        groups: List[Tuple[int, int]],
        pod_cursor: Dict[int, int],
        topo: Topology,
        claims: List[InFlightNodeClaim],
        divergent: List[Pod],
    ) -> bool:
        """Materialize a fresh slot's claim straight from the prep tensors.

        The per-group viability mask — template ITs ∧ class requirement
        compat (class_it, the same kernels the FFD scan used, property-tested
        against the host algebra) ∧ float64 resource fit ∧ offering
        availability under the joined zone/capacity-type masks — replaces
        the O(groups × instance-types) Python filter. Requirements and
        request dicts are still folded through the host algebra once per
        class, so the returned claim is indistinguishable from the
        add()-built one. Returns False to fall back wholesale (min-values or
        host ports in play), leaving pod cursors untouched."""
        if template.requirements.has_min_values():
            return False
        for ci, _k in groups:
            cls = prep.classes[ci]
            if cls.pods and (
                cls.pods[0].host_ports or cls.requirements.has_min_values()
            ):
                return False

        # The whole plane outcome is a pure function of the composition
        # (si, groups) given prep — and hundreds of slots repeat a handful
        # of compositions, so the per-class trial loop, request folding,
        # requirement joining, and final filter all cache on that shape;
        # per-slot work reduces to cursor advancement + claim assembly.
        shape = (si, tuple(groups))
        cached = self._composition_cache.get(shape)
        if cached is None:
            cached = self._decode_composition(prep, si, template, groups)
            self._composition_cache[shape] = cached
        committed_counts, remaining, requests_proto, reqs_proto = cached

        committed_set = {ci for ci, _ in committed_counts}
        pods_all: List[Pod] = []
        for ci, k in groups:
            cls = prep.classes[ci]
            start = pod_cursor[ci]
            pods = cls.pods[start : start + k]
            pod_cursor[ci] = start + k
            if not pods:
                continue
            if ci in committed_set and remaining:
                pods_all.extend(pods)
            else:
                divergent.extend(pods)
        if pods_all:
            claim = InFlightNodeClaim(
                template, topo, self.daemon_overhead[si], list(remaining)
            )
            claim.requirements.add(*(r.copy() for r in reqs_proto))
            claim.pods = pods_all
            claim.requests = dict(requests_proto)
            claims.append(claim)
        return True

    def _decode_composition(
        self, prep: _Prepared, si: int, template, groups: List[Tuple[int, int]]
    ):
        """Evaluate one composition shape through the plane algebra: the
        per-group viability mask — template ITs ∧ class requirement compat
        (class_it, the same kernels the FFD scan used, property-tested
        against the host algebra) ∧ quantized-integer resource fit (the
        device kernel's exact arithmetic, so slots packed exactly full are
        not rejected over raw-float drift) ∧ offering availability under
        the joined zone/capacity-type masks — then one final
        requirements-only filter_instance_types against the JOINED
        requirements (classes can be pairwise-IT-compatible yet jointly
        narrower)."""
        Z, CT = prep.n_zones, prep.n_cts
        cm = prep.class_masks
        T = len(prep.catalog)
        mask = prep.tmpl_it_np[si].copy()
        req_vec = prep.tmpl_overhead64q[si].copy()
        zmask = prep.tmpl_mask_np[si, prep.zone_kid, :Z].copy()
        ctmask = prep.tmpl_mask_np[si, prep.ct_kid, :CT].copy()
        requests = dict(self.daemon_overhead[si])
        committed_counts: List[Tuple[int, int]] = []

        for ci, k in groups:
            cls = prep.classes[ci]
            if not cls.pods:
                continue
            trial_req = req_vec.copy()
            for _ in range(k):
                trial_req += prep.class_requests64q[ci]
            trial_z = zmask & cm.mask[ci, prep.zone_kid, :Z]
            trial_ct = ctmask & cm.mask[ci, prep.ct_kid, :CT]
            fits = (trial_req[None, :] <= prep.it_alloc64q).all(axis=1)
            off_ok = (
                prep.off_avail_np
                & trial_z[None, :, None]
                & trial_ct[None, None, :]
            ).any(axis=(1, 2))
            trial = mask & prep.class_it[ci] & fits & off_ok
            if not trial.any():
                continue  # caller diverges this class (not in committed)
            mask, req_vec, zmask, ctmask = trial, trial_req, trial_z, trial_ct
            requests = resutil.merge_repeated(
                requests, resutil.requests_for_pods(cls.pods[0]), k
            )
            committed_counts.append((ci, k))

        remaining: list = []
        reqs_proto: list = []
        if committed_counts:
            options = [prep.catalog[i] for i in np.nonzero(mask[:T])[0]]
            joined = Requirements()
            joined.add(*(r.copy() for r in template.requirements.values()))
            for ci, _k in committed_counts:
                reqs = prep.classes[ci].requirements
                reqs_proto.extend(reqs.values())
                joined.add(*(r.copy() for r in reqs.values()))
            remaining = filter_instance_types(options, joined, {}).remaining
            if not remaining:
                # jointly-incompatible composition: everything diverges
                committed_counts = []
                reqs_proto = []
        return committed_counts, remaining, requests, reqs_proto

    def _host_fallback_add(
        self,
        pod: Pod,
        claims: List[InFlightNodeClaim],
        existing_sims: List[ExistingNodeSim],
        topo: Topology,
        pod_requests: Optional[dict] = None,
    ) -> Optional[str]:
        """Host placement via the shared greedy policy (place_pod), with the
        pools' remaining limits so fallback claims respect NodePool limits
        exactly like the greedy path (scheduler.go:417-434)."""
        if pod_requests is None:
            pod_requests = resutil.requests_for_pods(pod)
        return place_pod(
            pod,
            pod_requests,
            existing_sims,
            claims,
            self.templates,
            {id(t): o for t, o in zip(self.templates, self.daemon_overhead)},
            topo,
            getattr(self, "_round_remaining", {}),
        )
