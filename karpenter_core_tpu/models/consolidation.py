"""Batched multi-node consolidation prefix evaluation — hot loop #2.

The reference binary-searches the largest candidate prefix whose removal
still schedules everything (multinodeconsolidation.go:110-162): ~log2(100)
full Scheduler.Solve() simulations, each over the whole cluster. Here every
prefix is evaluated in ONE device call: the FFD scan is vmapped over a
prefix axis where

* candidate slots are masked out per prefix (kind=0 — the scan never
  places onto them), and
* the removed candidates' reschedulable pods join the pod classes with
  per-prefix counts,

so prefix p's scan sees exactly the cluster SimulateScheduling would build
for candidates[:p]. The returned schedulability frontier (all pods placed,
new-node count) is the quantity the binary search was probing; the exact
host pipeline (price filters, spot rules) then runs once at the frontier.

Pods with topology constraints take the host path (callers fall back to
binary search when any candidate carries them).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
    Topology,
    has_topology_constraints,
)
from karpenter_core_tpu.models.provisioner import DeviceScheduler, _SlotOverflow
from karpenter_core_tpu.ops.ffd import ClassStep, SlotState, ffd_step
from karpenter_core_tpu.parallel import mesh as pmesh
from karpenter_core_tpu.solver.snapshot import _spec_signature


def _ffd_scan(state, classes, statics, it_price, n_existing):
    final, (takes, unplaced) = jax.lax.scan(
        lambda st, c: ffd_step(st, c, statics), state, classes
    )
    # price lower bound of the fresh nodes this prefix would launch: each
    # fresh slot's cheapest still-viable type (its final option set is a
    # SUPERSET of the claim the host would build, so this never exceeds the
    # true replacement price — a sound skip-filter for the host's
    # cheaper-than-candidates rule, SURVEY §7.7's device price tensors)
    idx = jnp.arange(final.kind.shape[0])
    fresh = (idx >= n_existing) & (idx < final.next_free)
    slot_price = jnp.min(
        jnp.where(final.itmask, it_price[None, :], jnp.inf), axis=1
    )
    price_lb = jnp.sum(jnp.where(fresh, slot_price, 0.0))
    return final.next_free, jnp.sum(unplaced), final.overflow, price_lb


# graftlint: disable=GL103 -- must NOT donate: the state is prep.init_state
# from the DeviceScheduler's prepared cache, reused by later solves and
# sweeps against the same cluster; donation would invalidate the cache
@jax.jit
def _prefix_scan(state: SlotState, classes: ClassStep, statics, kind_batch,
                 count_batch, it_price, n_existing):
    """vmap the FFD scan over the prefix axis: only the slot kinds and the
    class counts vary per prefix; masks/capacities/statics are shared."""

    def one(kind, counts):
        st = state._replace(kind=kind)
        cl = classes._replace(count=counts)
        return _ffd_scan(st, cl, statics, it_price, n_existing)

    return jax.vmap(one)(kind_batch, count_batch)


def prefix_batches(
    prep, base_pods: List, candidate_pods: List[List]
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-prefix slot kinds and class counts for the vmapped sweep.

    Prefix p removes candidate slots [0, p] (kind=0) and adds candidates
    0..p's reschedulable pods to the class counts; base pods always count.
    Candidate slots must occupy the first len(candidate_pods) positions of
    prep.init_state (candidate-first existing-node order)."""
    P = len(candidate_pods)
    C = len(prep.classes)

    # graftlint: disable=GL503 -- the sweep's scheduler is constructed
    # devices=1 (frontier_core shards the PREFIX axis, never the slot
    # axis), so this is a single-device fetch of one [N] int8 plane per
    # sweep — not a cross-device gather
    base_kind = np.asarray(prep.init_state.kind)
    kind_batch = np.tile(base_kind, (P, 1))
    for p in range(P):
        kind_batch[p, : p + 1] = 0

    # label_aware=False matches the empty Topology() the sweep's prep was
    # grouped under (the frontier bails on any topology-coupled pod)
    sig_to_ci = {
        _spec_signature(cls.pods[0], False): ci
        for ci, cls in enumerate(prep.classes)
    }
    base_counts = np.zeros((C,), dtype=np.int32)
    for pod in base_pods:
        base_counts[sig_to_ci[_spec_signature(pod, False)]] += 1
    count_batch = np.tile(base_counts, (P, 1))
    for i, pods in enumerate(candidate_pods):
        for pod in pods:
            count_batch[i:, sig_to_ci[_spec_signature(pod, False)]] += 1
    return kind_batch, count_batch


def schedulability_frontier(
    provisioner,
    cluster,
    candidates: List,
    max_slots: int = 1024,
) -> Optional[List[Tuple[bool, int, float]]]:
    """Per-prefix (all pods scheduled, new nodes needed, fresh-node price
    lower bound) for prefixes 1..len(candidates). The price bound is the
    sum over fresh slots of the cheapest still-viable type — a true lower
    bound only when the device packed the fresh nodes like the host
    simulation would (callers must treat bound-failing sizes as
    deprioritized, not impossible). None when the batched path can't
    represent the problem (topology-coupled pods) — callers binary-search
    instead."""
    base_pods = provisioner.pending_pods() + provisioner.deleting_node_pods()
    if any(has_topology_constraints(p) for p in base_pods):
        return None
    for c in candidates:
        if any(has_topology_constraints(p) for p in c.reschedulable_pods):
            return None

    excluded = {c.name for c in candidates}
    keep_nodes = [n for n in cluster.sim_nodes() if n.name not in excluded]
    cand_nodes = []
    for c in candidates:
        for n in cluster.sim_nodes():
            if n.name == c.name:
                cand_nodes.append(n)
                break
    if len(cand_nodes) != len(candidates):
        return None

    nodepools = provisioner.ready_nodepools()
    instance_types = {
        np_.name: provisioner.cloud_provider.get_instance_types(np_)
        for np_ in nodepools
    }
    # the sweep's price bound and repack viability must see the same ICE'd
    # offerings the solve does, or consolidation plans a replacement onto a
    # stocked-out offering that the launch then fails
    cache = getattr(provisioner, "unavailable_offerings", None)
    if cache is not None:
        from karpenter_core_tpu.cloudprovider.types import apply_unavailable

        instance_types = apply_unavailable(instance_types, cache.snapshot())
    candidate_pods = [c.reschedulable_pods for c in candidates]
    daemonset_pods = provisioner.daemonset_pods()

    # sidecar mode: the sweep crosses the same RPC seam as the solve; a
    # dead/slow sidecar degrades to the host binary search (None), exactly
    # like an unrepresentable problem
    client = getattr(provisioner, "solver_client", None)
    if client is not None:
        from karpenter_core_tpu.solver.remote import remote_frontier

        return remote_frontier(
            client,
            nodepools,
            instance_types,
            cand_nodes,
            keep_nodes,
            daemonset_pods,
            base_pods,
            candidate_pods,
            max_slots=max_slots,
        )
    # in-proc sweeps follow the solve path's device-count choice (the
    # operator threads --solver-devices through device_scheduler_opts);
    # a sidecar owns its own device count (solverd --devices)
    dev_opts = getattr(provisioner, "device_scheduler_opts", None) or {}
    frontier = frontier_core(
        nodepools,
        instance_types,
        cand_nodes,
        keep_nodes,
        daemonset_pods,
        base_pods,
        candidate_pods,
        max_slots=max_slots,
        devices=dev_opts.get("devices", 1),
    )
    # the same structural trust anchor the sidecar path applies
    # (solver/remote.remote_frontier): a defective frontier degrades to
    # the caller's host binary search, never into a disruption command
    from karpenter_core_tpu.solver.verify import verify_frontier

    defect = verify_frontier(frontier)
    if defect is not None:
        from karpenter_core_tpu.metrics import wiring as m

        m.SOLVER_RESULT_REJECTED.inc(
            {"reason": "structure", "path": "frontier"}
        )
        return None
    return frontier


def frontier_core(
    nodepools,
    instance_types,
    cand_nodes,
    keep_nodes,
    daemonset_pods,
    base_pods: List,
    candidate_pods: List[List],
    max_slots: int = 1024,
    devices: int = 1,
) -> Optional[List[Tuple[bool, int, float]]]:
    """The device sweep proper, over already-gathered inputs — runnable
    in-process or behind the solverd sidecar (solver/service.py decodes a
    frontier request straight into this signature).

    With ``devices > 1`` the INDEPENDENT prefix axis shards over the mesh
    (batch_sharding): each device evaluates its prefix subset against a
    replicated SlotState with zero cross-device traffic inside the scan —
    the prefix count (~100 candidates) dwarfs the device count, so
    prefix-parallel beats slot-parallel for the sweep."""
    all_pods = list(base_pods)
    for pods in candidate_pods:
        all_pods.extend(pods)

    # the sweep shards the PREFIX axis, so its scheduler must NOT
    # pre-shard the slot axis (devices=1 here): the state/class planes
    # land once, get replicated across the prefix mesh in one placement
    # below, and never pay a shard-then-regather round trip
    n_dev = pmesh.resolve_devices(devices)
    # candidate slots first so prefix p masks slots [0, p)
    sched = DeviceScheduler(
        nodepools,
        instance_types,
        existing_nodes=cand_nodes + keep_nodes,
        daemonset_pods=daemonset_pods,
        max_slots=max_slots,
        devices=1,
    )
    # DeviceScheduler sorts existing nodes; force candidate-first order back
    sched.existing_nodes = cand_nodes + keep_nodes
    try:
        prep = sched._prepare(all_pods, max_slots, Topology())
    except _SlotOverflow:
        return None  # cluster wider than the slot array: binary search

    P = len(candidate_pods)
    if P == 0:
        return []
    E = len(sched.existing_nodes)
    kind_batch, count_batch = prefix_batches(prep, base_pods, candidate_pods)

    classes = sched._class_steps(prep)
    Jp = int(classes.count.shape[0])
    if count_batch.shape[1] < Jp:  # steps pad to a bucketed count
        count_batch = np.pad(
            count_batch, ((0, 0), (0, Jp - count_batch.shape[1]))
        )
    if n_dev > 1:
        # shard the prefix axis over the mesh; the (single-device,
        # uncommitted) state/class/static planes commit replicated in ONE
        # placement each. Pad P to a device multiple with copies of the
        # last prefix and slice the verdicts back below.
        mesh = pmesh.slot_mesh(n_dev)
        repl = pmesh.replicated(mesh)
        pad_p = pmesh.pad_to_devices(P, n_dev) - P
        if pad_p:
            kind_batch = np.concatenate(
                [kind_batch, np.repeat(kind_batch[-1:], pad_p, axis=0)]
            )
            count_batch = np.concatenate(
                [count_batch, np.repeat(count_batch[-1:], pad_p, axis=0)]
            )
        psh = pmesh.batch_sharding(mesh, 2)
        state = jax.device_put(
            prep.init_state, jax.tree.map(lambda _: repl, prep.init_state)
        )
        cls = jax.device_put(classes, jax.tree.map(lambda _: repl, classes))
        statics = jax.device_put(
            prep.statics, jax.tree.map(lambda _: repl, prep.statics)
        )
        kind_d = jax.device_put(kind_batch, psh)
        count_d = jax.device_put(count_batch, psh)
    else:
        state, cls, statics = prep.init_state, classes, prep.statics
        kind_d = jnp.asarray(kind_batch)
        count_d = jnp.asarray(count_batch)
    next_free, unplaced, overflow, price_lb = _prefix_scan(
        state,
        cls,
        statics,
        kind_d,
        count_d,
        jnp.asarray(_it_price_vector(prep)),
        jnp.int32(E),
    )
    next_free = np.asarray(next_free)[:P]
    unplaced = np.asarray(unplaced)[:P]
    overflow = np.asarray(overflow)[:P]
    price_lb = np.asarray(price_lb)[:P]
    # an overflowed prefix silently counted spilled pods as placed — it is
    # NOT schedulable evidence
    return [
        (
            int(unplaced[p]) == 0 and not bool(overflow[p]),
            int(next_free[p]) - E,
            float(price_lb[p]),
        )
        for p in range(P)
    ]


def _it_price_vector(prep) -> np.ndarray:
    """Cheapest available offering price per catalog type, padded to the
    statics' bucketed T axis with +inf (never cheapest)."""
    Tp = int(prep.statics.it_alloc.shape[0])
    out = np.full((Tp,), np.inf, dtype=np.float32)
    for ti, it in enumerate(prep.catalog):
        available = it.offerings.available()
        if available:
            out[ti] = min(o.price for o in available)
    return out
