"""Status conditions (the operatorpkg condition model the reference relies on)."""
from __future__ import annotations

from dataclasses import dataclass, field

from karpenter_core_tpu.utils import timesource
from typing import Optional

CONDITION_TRUE = "True"
CONDITION_FALSE = "False"
CONDITION_UNKNOWN = "Unknown"


@dataclass
class Condition:
    type: str
    status: str = CONDITION_UNKNOWN
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=timesource.now)


class ConditionSet:
    """Mutable set of typed conditions with a root 'Ready' aggregation."""

    def __init__(self, *types: str):
        self._conditions: dict = {}
        self._types = list(types)

    def get(self, cond_type: str) -> Optional[Condition]:
        return self._conditions.get(cond_type)

    def set(
        self,
        cond_type: str,
        status: str,
        reason: str = "",
        message: str = "",
        now: Optional[float] = None,
    ) -> bool:
        """Returns True if the condition transitioned. Controllers pass
        ``now`` from their injected clock; the timesource default covers
        ad-hoc construction."""
        existing = self._conditions.get(cond_type)
        if existing is not None and existing.status == status:
            existing.reason = reason
            existing.message = message
            return False
        cond = Condition(
            type=cond_type, status=status, reason=reason, message=message
        )
        if now is not None:
            cond.last_transition_time = now
        self._conditions[cond_type] = cond
        return True

    def set_true(
        self, cond_type: str, reason: str = "", now: Optional[float] = None
    ) -> bool:
        return self.set(cond_type, CONDITION_TRUE, reason, now=now)

    def set_false(
        self,
        cond_type: str,
        reason: str = "",
        message: str = "",
        now: Optional[float] = None,
    ) -> bool:
        return self.set(cond_type, CONDITION_FALSE, reason, message, now=now)

    def clear(self, cond_type: str) -> bool:
        return self._conditions.pop(cond_type, None) is not None

    def is_true(self, cond_type: str) -> bool:
        c = self._conditions.get(cond_type)
        return c is not None and c.status == CONDITION_TRUE

    def is_false(self, cond_type: str) -> bool:
        c = self._conditions.get(cond_type)
        return c is not None and c.status == CONDITION_FALSE

    def root_is_true(self, root_types) -> bool:
        return all(self.is_true(t) for t in root_types)

    def all(self) -> list:
        return list(self._conditions.values())
