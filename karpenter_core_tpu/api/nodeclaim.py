"""NodeClaim — a requested machine (reference: pkg/apis/v1/nodeclaim.go:27-156,
nodeclaim_status.go:25-78). Spec is immutable after creation."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_core_tpu.api.duration import NillableDuration
from karpenter_core_tpu.api.objects import ObjectMeta, ResourceList
from karpenter_core_tpu.api.status import ConditionSet

# Condition types (reference: nodeclaim_status.go:25-34)
COND_LAUNCHED = "Launched"
COND_REGISTERED = "Registered"
COND_INITIALIZED = "Initialized"
COND_CONSOLIDATABLE = "Consolidatable"
COND_DRIFTED = "Drifted"
COND_INSTANCE_TERMINATING = "InstanceTerminating"
COND_CONSISTENT_STATE_FOUND = "ConsistentStateFound"
COND_DISRUPTION_REASON = "DisruptionReason"
COND_READY = "Ready"

LIFECYCLE_CONDITIONS = (COND_LAUNCHED, COND_REGISTERED, COND_INITIALIZED)


@dataclass
class NodeClassRef:
    group: str = ""
    kind: str = ""
    name: str = ""


@dataclass
class NodeClaimSpec:
    # scheduling requirements: list[api.objects.NodeSelectorRequirement]
    requirements: list = field(default_factory=list)
    resources_requests: ResourceList = field(default_factory=dict)
    node_class_ref: Optional[NodeClassRef] = None
    taints: list = field(default_factory=list)
    startup_taints: list = field(default_factory=list)
    expire_after: NillableDuration = field(default_factory=NillableDuration)
    termination_grace_period: Optional[float] = None  # seconds


@dataclass
class NodeClaimStatus:
    node_name: str = ""
    provider_id: str = ""
    image_id: str = ""
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    last_pod_event_time: Optional[float] = None


@dataclass
class NodeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeClaimSpec = field(default_factory=NodeClaimSpec)
    status: NodeClaimStatus = field(default_factory=NodeClaimStatus)
    conditions: ConditionSet = field(default_factory=ConditionSet)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def nodepool_name(self) -> str:
        from karpenter_core_tpu.api import labels as apilabels

        return self.metadata.labels.get(apilabels.NODEPOOL_LABEL_KEY, "")

    def is_launched(self) -> bool:
        return self.conditions.is_true(COND_LAUNCHED)

    def is_registered(self) -> bool:
        return self.conditions.is_true(COND_REGISTERED)

    def is_initialized(self) -> bool:
        return self.conditions.is_true(COND_INITIALIZED)
