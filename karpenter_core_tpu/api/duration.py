"""NillableDuration — a duration that may be 'Never' (reference: pkg/apis/v1/duration.go)."""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(h|m|s|ms)")
_UNIT = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3}


def parse_duration(s: "str | int | float | None") -> Optional[float]:
    """Parse a Go-style duration ('1h30m', '15s') to seconds; None/'Never' -> None."""
    if s is None:
        return None
    if isinstance(s, (int, float)):
        return float(s)
    s = s.strip()
    if s == "Never":
        return None
    if s == "0":
        return 0.0
    matches = _DUR_RE.findall(s)
    if not matches or "".join(n + u for n, u in matches) != s:
        raise ValueError(f"cannot parse duration {s!r}")
    return sum(float(n) * _UNIT[u] for n, u in matches)


@dataclass(frozen=True)
class NillableDuration:
    """seconds=None means Never."""

    seconds: Optional[float] = None

    @classmethod
    def parse(cls, s) -> "NillableDuration":
        return cls(parse_duration(s))

    @property
    def is_never(self) -> bool:
        return self.seconds is None

    def __str__(self) -> str:
        return "Never" if self.seconds is None else f"{self.seconds:g}s"


NEVER = NillableDuration(None)
