"""Standalone kubernetes-shaped object model.

The framework is self-contained (no kube-apiserver in the loop for tests and
benchmarks — the in-memory ``kube`` store plays envtest's role, reference:
pkg/test/environment.go:60-80), so the core API machinery objects the
reference gets from client-go are defined here as plain dataclasses.

Resource quantities are float64 (cpu in cores, memory/storage in bytes).
The reference uses apimachinery's infinite-precision Quantity; every value the
scheduler actually compares is well inside float64's 2^53 integer range.
"""
from __future__ import annotations

import itertools
import re
from dataclasses import dataclass, field

from typing import Optional

# ---------------------------------------------------------------------------
# Quantities

_QUANTITY_RE = re.compile(r"^([+-]?[0-9.]+(?:[eE][+-]?[0-9]+)?)([A-Za-z]*)$")

_SUFFIX = {
    "": 1.0,
    "m": 1e-3,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
    "Ki": 2.0**10,
    "Mi": 2.0**20,
    "Gi": 2.0**30,
    "Ti": 2.0**40,
    "Pi": 2.0**50,
    "Ei": 2.0**60,
}


def parse_quantity(value: "str | int | float") -> float:
    """Parse a kubernetes quantity string ('100m', '1Gi', '2') to a float."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _QUANTITY_RE.match(value.strip())
    if not m:
        raise ValueError(f"cannot parse quantity {value!r}")
    number, suffix = m.groups()
    if suffix not in _SUFFIX:
        raise ValueError(f"unknown quantity suffix {suffix!r} in {value!r}")
    return float(number) * _SUFFIX[suffix]


# Resource names (mirror corev1.ResourceName values)
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"

ResourceList = dict  # dict[str, float]


def resource_list(**kwargs) -> ResourceList:
    """Build a ResourceList from keyword args; 'memory'/'ephemeral_storage' keys normalized."""
    out = {}
    for k, v in kwargs.items():
        out[k.replace("_", "-")] = parse_quantity(v)
    return out


# ---------------------------------------------------------------------------
# Metadata

_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"uid-{next(_uid_counter):08d}"


@dataclass
class OwnerReference:
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)
    finalizers: list = field(default_factory=list)
    owner_references: list = field(default_factory=list)
    # 0.0 = unset; the kube store stamps it from ITS clock on create, so
    # multiple stores/operators with different clocks never cross-contaminate
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0
    generation: int = 1


# ---------------------------------------------------------------------------
# Taints & tolerations

TAINT_EFFECT_NO_SCHEDULE = "NoSchedule"
TAINT_EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_EFFECT_NO_EXECUTE = "NoExecute"

TOLERATION_OP_EXISTS = "Exists"
TOLERATION_OP_EQUAL = "Equal"


@dataclass(frozen=True)
class Taint:
    key: str
    effect: str
    value: str = ""

    def __str__(self) -> str:
        return f"{self.key}={self.value}:{self.effect}" if self.value else f"{self.key}:{self.effect}"


@dataclass(frozen=True)
class Toleration:
    """Mirror of corev1.Toleration.ToleratesTaint semantics."""

    key: str = ""
    operator: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""
    toleration_seconds: Optional[float] = None

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == TOLERATION_OP_EXISTS:
            return True
        if self.operator in ("", TOLERATION_OP_EQUAL):
            return self.value == taint.value
        return False  # unknown operators never tolerate (corev1 semantics)


# ---------------------------------------------------------------------------
# Node selector / affinity

@dataclass(frozen=True)
class NodeSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: tuple = ()
    min_values: Optional[int] = None  # NodePool flexibility extension


@dataclass(frozen=True)
class NodeSelectorTerm:
    match_expressions: tuple = ()  # tuple[NodeSelectorRequirement]


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass
class NodeAffinity:
    required: list = field(default_factory=list)  # list[NodeSelectorTerm] (OR'd)
    preferred: list = field(default_factory=list)  # list[PreferredSchedulingTerm]


@dataclass(frozen=True)
class LabelSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: tuple = ()


@dataclass(frozen=True)
class LabelSelector:
    match_labels: tuple = ()  # tuple[(key, value)]
    match_expressions: tuple = ()  # tuple[LabelSelectorRequirement]

    def matches(self, labels: dict) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        for expr in self.match_expressions:
            has = expr.key in labels
            val = labels.get(expr.key)
            if expr.operator == "In":
                if not has or val not in expr.values:
                    return False
            elif expr.operator == "NotIn":
                if has and val in expr.values:
                    return False
            elif expr.operator == "Exists":
                if not has:
                    return False
            elif expr.operator == "DoesNotExist":
                if has:
                    return False
        return True


@dataclass(frozen=True)
class PodAffinityTerm:
    topology_key: str
    label_selector: Optional[LabelSelector] = None
    namespaces: tuple = ()


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    pod_affinity_term: PodAffinityTerm


@dataclass
class PodAffinity:
    required: list = field(default_factory=list)  # list[PodAffinityTerm]
    preferred: list = field(default_factory=list)  # list[WeightedPodAffinityTerm]


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAffinity] = None


@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None


# ---------------------------------------------------------------------------
# Pod

POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"


@dataclass(frozen=True)
class PodVolume:
    """One pod volume spec entry. Only PVC-backed shapes matter to
    scheduling (emptyDir/hostPath etc. are represented by pvc_name=None and
    ignored, reference volumetopology.go:86-88)."""

    name: str
    pvc_name: Optional[str] = None  # persistentVolumeClaim.claimName
    ephemeral: bool = False  # generic ephemeral volume -> PVC "<pod>-<name>"


# Native-sidecar restart policy marker (k8s ContainerRestartPolicyAlways).
CONTAINER_RESTART_ALWAYS = "Always"


@dataclass
class Container:
    """One container spec entry — just the scheduling-relevant surface.

    ``restart_policy`` only matters on init containers: "Always" marks a
    native sidecar whose requests persist for the pod's lifetime
    (resources.go:96-128 podRequests)."""

    name: str = ""
    resource_requests: ResourceList = field(default_factory=dict)
    resource_limits: ResourceList = field(default_factory=dict)
    restart_policy: Optional[str] = None


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    # Aggregated resource requests. When ``containers``/``init_containers``
    # are present this is DERIVED at construction via the reference's
    # ceiling rule (max of container sum vs init-container peaks, plus
    # overhead — resources.go:96-128); providing it directly is the
    # flat-request convenience path for workloads without container specs.
    resource_requests: ResourceList = field(default_factory=dict)
    # Derived alongside requests when container specs are present
    # (resources.go podLimits; exported by the node metrics exporter via
    # utils/resources.limits_for_pods, statenode.go:429's consumer role).
    resource_limits: ResourceList = field(default_factory=dict)
    # Container-level spec (utils/resources.ceiling derives the aggregate).
    containers: list = field(default_factory=list)
    init_containers: list = field(default_factory=list)
    # RuntimeClass pod overhead, added on top of the container aggregate
    # (resources.go:124-126).
    overhead: ResourceList = field(default_factory=dict)
    node_selector: dict = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: list = field(default_factory=list)
    topology_spread_constraints: list = field(default_factory=list)
    host_ports: list = field(default_factory=list)  # list[(ip, port, protocol)]
    volumes: list = field(default_factory=list)  # list[PodVolume]
    # zone/etc requirements derived from this pod's PVCs, stamped by
    # VolumeTopology.inject pre-solve; AND'd into the pod's requirements by
    # Requirements.from_pod so relaxation can never strip them
    # (volumetopology.go:68-72's per-term injection, lifted out of the spec)
    volume_requirements: list = field(default_factory=list)
    # {csi driver -> set of pvc keys}, resolved pre-solve for attach-limit
    # accounting without a client in the scheduler (volumeusage.go GetVolumes)
    resolved_volumes: Optional[dict] = None
    priority: int = 0
    priority_class_name: str = ""
    # k8s defaults terminationGracePeriodSeconds to 30
    termination_grace_period_seconds: float = 30.0
    preemption_policy: str = "PreemptLowerPriority"
    scheduling_gates: list = field(default_factory=list)
    node_name: str = ""
    phase: str = POD_PENDING
    # conditions: list of (type, status, reason)
    conditions: list = field(default_factory=list)
    is_daemonset: bool = False
    is_mirror: bool = False

    def __post_init__(self):
        if self.containers or self.init_containers:
            from karpenter_core_tpu.utils import resources as _res

            self.resource_requests = _res.pod_requests(self)
            self.resource_limits = _res.pod_limits(self)
        elif self.overhead:
            # flat-request pods with RuntimeClass overhead: overhead lands on
            # top of the provided requests (resources.go:124-126), it does
            # not replace them
            from karpenter_core_tpu.utils import resources as _res

            self.resource_requests = _res.merge(
                self.resource_requests, self.overhead
            )
            if self.resource_limits:
                self.resource_limits = _res.merge(
                    self.resource_limits, self.overhead
                )

    @property
    def uid(self) -> str:
        return self.metadata.uid

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


# ---------------------------------------------------------------------------
# DaemonSet (enough surface for daemon-overhead accounting,
# reference: pkg/controllers/provisioning/provisioner.go:409-434)

@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    pod_template: Optional["Pod"] = None

    @property
    def name(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------------------
# Node

@dataclass
class NodeStatus:
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    conditions: list = field(default_factory=list)  # list[(type, status)]
    phase: str = ""


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provider_id: str = ""
    taints: list = field(default_factory=list)
    unschedulable: bool = False
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def labels(self) -> dict:
        return self.metadata.labels

    def ready(self) -> bool:
        return any(t == "Ready" and s == "True" for t, s, *_ in self.status.conditions)


# ---------------------------------------------------------------------------
# Storage (PVC/PV/StorageClass/CSINode/VolumeAttachment — the surface the
# volume-aware scheduling + termination paths consume; reference:
# volumetopology.go:45-150, volumeusage.go:82-150,
# node/termination/controller.go:190-201)

@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    storage_class_name: Optional[str] = None
    volume_name: str = ""  # bound PV name ("" = unbound)

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    # required node-affinity terms (ORed; zone-pinning for zonal volumes)
    node_affinity_required: list = field(default_factory=list)  # [NodeSelectorTerm]
    csi_driver: str = ""  # spec.csi.driver ("" = non-CSI)
    local: bool = False  # spec.local / spec.hostPath: hostname affinity is
    host_path: bool = False  # dropped on reschedule (volumetopology.go:141-146)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    # [(key, values)] from allowedTopologies[0].matchLabelExpressions
    allowed_topologies: list = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class CSINode:
    """Per-node CSI driver attach limits (name == node name)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    drivers: list = field(default_factory=list)  # [(driver name, allocatable)]

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class VolumeAttachment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    attacher: str = ""
    node_name: str = ""
    pv_name: str = ""

    @property
    def name(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------------------
# PodDisruptionBudget (policy/v1; the surface pdb.NewLimits and the eviction
# API consume — reference pkg/utils/pdb/pdb.go:33-118)

@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    # exactly one of these is set; int = absolute, str "N%" = percentage
    min_available: "int | str | None" = None
    max_unavailable: "int | str | None" = None
    unhealthy_pod_eviction_policy: str = "IfHealthyBudget"  # | AlwaysAllow

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"
