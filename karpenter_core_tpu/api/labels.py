"""Well-known label / annotation / taint vocabulary.

API-surface compatible with the reference CRDs (reference:
pkg/apis/v1/labels.go:30-105, pkg/apis/v1/taints.go). These strings are the
closed-world vocabulary that the solver's mask tensors are built over
(SURVEY.md §2.2: "these become the vocabulary of the mask tensors").
"""
from __future__ import annotations

GROUP = "karpenter.sh"

# kubernetes.io well-known label keys
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_TOPOLOGY_ZONE = "topology.kubernetes.io/zone"
LABEL_TOPOLOGY_REGION = "topology.kubernetes.io/region"
LABEL_INSTANCE_TYPE = "node.kubernetes.io/instance-type"
LABEL_ARCH = "kubernetes.io/arch"
LABEL_OS = "kubernetes.io/os"
LABEL_WINDOWS_BUILD = "node.kubernetes.io/windows-build"

ARCHITECTURE_AMD64 = "amd64"
ARCHITECTURE_ARM64 = "arm64"
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"

# Framework-specific labels
NODEPOOL_LABEL_KEY = f"{GROUP}/nodepool"
NODE_INITIALIZED_LABEL_KEY = f"{GROUP}/initialized"
NODE_REGISTERED_LABEL_KEY = f"{GROUP}/registered"
CAPACITY_TYPE_LABEL_KEY = f"{GROUP}/capacity-type"

# Network-topology hierarchy (ISSUE 20). Two optional levels below the
# kubernetes zone: a rack (one ICI/ToR domain) and a superpod (a group of
# racks behind one spine block). Offerings and existing nodes carry them;
# the solver lowers the hierarchy into a per-domain-pair hop matrix
# (ops/topoplan) and a rank-aware fill order (ops/ffd). Absent labels mean
# "topology unknown" and the subsystem stays fully disengaged.
LABEL_TOPOLOGY_RACK = f"topology.{GROUP}/rack"
LABEL_TOPOLOGY_SUPERPOD = f"topology.{GROUP}/superpod"

# Annotations
DO_NOT_DISRUPT_ANNOTATION_KEY = f"{GROUP}/do-not-disrupt"
NODEPOOL_HASH_ANNOTATION_KEY = f"{GROUP}/nodepool-hash"
NODEPOOL_HASH_VERSION_ANNOTATION_KEY = f"{GROUP}/nodepool-hash-version"
# bumped whenever static_hash()'s algorithm/fields change; drift compares
# hashes only when versions match (hash/controller.go migration)
HASH_VERSION = "v3"
NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY = (
    f"{GROUP}/nodeclaim-termination-timestamp"
)

# Finalizers
TERMINATION_FINALIZER = f"{GROUP}/termination"

# Taint keys (reference: pkg/apis/v1/taints.go:26-41)
DISRUPTED_TAINT_KEY = f"{GROUP}/disrupted"
UNREGISTERED_TAINT_KEY = f"{GROUP}/unregistered"

RESTRICTED_LABEL_DOMAINS = frozenset({"kubernetes.io", "k8s.io", GROUP})

LABEL_DOMAIN_EXCEPTIONS = frozenset(
    {"kops.k8s.io", "node.kubernetes.io", "node-restriction.kubernetes.io"}
)

# Labels the controller understands and can narrow through NodePools or pods
# (reference: pkg/apis/v1/labels.go:78-88).
WELL_KNOWN_LABELS = frozenset(
    {
        NODEPOOL_LABEL_KEY,
        LABEL_TOPOLOGY_ZONE,
        LABEL_TOPOLOGY_REGION,
        LABEL_INSTANCE_TYPE,
        LABEL_ARCH,
        LABEL_OS,
        CAPACITY_TYPE_LABEL_KEY,
        LABEL_WINDOWS_BUILD,
        LABEL_TOPOLOGY_RACK,
        LABEL_TOPOLOGY_SUPERPOD,
    }
)

RESTRICTED_LABELS = frozenset({LABEL_HOSTNAME})

# Aliased (deprecated) label keys translated into well-known ones
# (reference: pkg/apis/v1/labels.go:97-104).
NORMALIZED_LABELS = {
    "failure-domain.beta.kubernetes.io/zone": LABEL_TOPOLOGY_ZONE,
    "failure-domain.beta.kubernetes.io/region": LABEL_TOPOLOGY_REGION,
    "beta.kubernetes.io/arch": LABEL_ARCH,
    "beta.kubernetes.io/os": LABEL_OS,
    "beta.kubernetes.io/instance-type": LABEL_INSTANCE_TYPE,
}


def is_restricted_label(key: str) -> bool:
    """True if the label may not be user-set (reference labels.go:108-120)."""
    if key in WELL_KNOWN_LABELS:
        return False
    domain = label_domain(key)
    if any(domain == d or domain.endswith("." + d) for d in RESTRICTED_LABEL_DOMAINS):
        if domain in LABEL_DOMAIN_EXCEPTIONS or any(
            domain.endswith("." + d) for d in LABEL_DOMAIN_EXCEPTIONS
        ):
            return False
        return True
    return key in RESTRICTED_LABELS


def is_restricted_node_label(key: str) -> bool:
    """True if the label must not be injected by the framework: well-known
    labels (cloud provider injects those), restricted domains, hostname
    (reference labels.go:118-131)."""
    if key in WELL_KNOWN_LABELS:
        return True
    domain = label_domain(key)
    if any(domain == d or domain.endswith("." + d) for d in LABEL_DOMAIN_EXCEPTIONS):
        return False
    if any(domain == d or domain.endswith("." + d) for d in RESTRICTED_LABEL_DOMAINS):
        return True
    return key in RESTRICTED_LABELS


def label_domain(key: str) -> str:
    return key.split("/", 1)[0] if "/" in key else ""
