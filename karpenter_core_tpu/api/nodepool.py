"""NodePool — the template of node possibilities plus disruption policy
(reference: pkg/apis/v1/nodepool.go:38-367)."""
from __future__ import annotations

import hashlib
import json
import math
import time
from dataclasses import dataclass, field
from typing import Optional

from karpenter_core_tpu.api.duration import NillableDuration
from karpenter_core_tpu.api.nodeclaim import NodeClassRef
from karpenter_core_tpu.api.objects import ObjectMeta, ResourceList
from karpenter_core_tpu.api.status import ConditionSet

CONSOLIDATION_POLICY_WHEN_EMPTY = "WhenEmpty"
CONSOLIDATION_POLICY_WHEN_EMPTY_OR_UNDERUTILIZED = "WhenEmptyOrUnderutilized"

# Disruption reasons (reference: nodepool.go DisruptionReason values)
REASON_UNDERUTILIZED = "Underutilized"
REASON_EMPTY = "Empty"
REASON_DRIFTED = "Drifted"
REASON_ALL = "All"  # budget wildcard

COND_NODEPOOL_VALIDATION_SUCCEEDED = "ValidationSucceeded"
COND_NODEPOOL_NODECLASS_READY = "NodeClassReady"


@dataclass
class Budget:
    """Disruption budget: max concurrently-disrupted nodes, optionally
    cron-windowed (reference: nodepool.go:320-367)."""

    nodes: str = "10%"  # absolute count or percentage
    schedule: Optional[str] = None  # cron expression; None = always active
    duration: Optional[float] = None  # seconds; required when schedule set
    reasons: list = field(default_factory=list)  # empty = all reasons

    def is_active(self, now: Optional[float] = None) -> bool:
        """Budget windows (nodepool.go:353-367). Cron schedules are matched by
        utils/cron.py; no schedule means always active."""
        if self.schedule is None:
            return True
        from karpenter_core_tpu.utils.cron import last_fire_before

        now = time.time() if now is None else now
        fired = last_fire_before(self.schedule, now)
        if fired is None:
            return False
        return now - fired < (self.duration or 0.0)

    def allowed_disruptions(self, total_nodes: int, now: Optional[float] = None) -> int:
        """Nodes this budget allows disrupting (nodepool.go:305-351).
        Percentages round UP, matching GetScaledValueFromIntOrPercent(.., true)
        — 5% of 10 nodes allows 1 rather than blocking everything."""
        if not self.is_active(now):
            return 1 << 31  # inactive budgets don't constrain
        if self.nodes.endswith("%"):
            pct = float(self.nodes[:-1]) / 100.0
            return math.ceil(pct * total_nodes - 1e-9)
        return int(self.nodes)


@dataclass
class Disruption:
    consolidate_after: NillableDuration = field(default_factory=lambda: NillableDuration(0.0))
    consolidation_policy: str = CONSOLIDATION_POLICY_WHEN_EMPTY_OR_UNDERUTILIZED
    budgets: list = field(default_factory=lambda: [Budget(nodes="10%")])


@dataclass
class Limits(dict):
    """Resource ceilings for a NodePool (nodepool.go:142-154)."""

    def exceeded_by(self, usage: ResourceList) -> list:
        errs = []
        for name, limit in self.items():
            if usage.get(name, 0.0) > limit:
                errs.append(
                    f"{name} resource usage of {usage.get(name, 0.0):g} exceeds limit of {limit:g}"
                )
        return errs


@dataclass
class NodeClaimTemplateSpec:
    """The NodeClaim template embedded in a NodePool."""

    requirements: list = field(default_factory=list)  # NodeSelectorRequirement (with min_values)
    node_class_ref: Optional[NodeClassRef] = None
    taints: list = field(default_factory=list)
    startup_taints: list = field(default_factory=list)
    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)
    expire_after: NillableDuration = field(default_factory=NillableDuration)
    termination_grace_period: Optional[float] = None


@dataclass
class NodePoolSpec:
    template: NodeClaimTemplateSpec = field(default_factory=NodeClaimTemplateSpec)
    disruption: Disruption = field(default_factory=Disruption)
    limits: Limits = field(default_factory=Limits)
    weight: int = 0  # higher = tried first


@dataclass
class NodePoolStatus:
    resources: ResourceList = field(default_factory=dict)  # in-use aggregation


@dataclass
class NodePool:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodePoolSpec = field(default_factory=NodePoolSpec)
    status: NodePoolStatus = field(default_factory=NodePoolStatus)
    conditions: ConditionSet = field(default_factory=ConditionSet)

    @property
    def name(self) -> str:
        return self.metadata.name

    def static_hash(self) -> str:
        """Drift hash over the static (non-requirement) template fields
        (reference: nodepool.go:277-283 Hash())."""
        payload = {
            "labels": self.spec.template.labels,
            "annotations": self.spec.template.annotations,
            "taints": [str(t) for t in self.spec.template.taints],
            "startup_taints": [str(t) for t in self.spec.template.startup_taints],
            "expire_after": str(self.spec.template.expire_after),
            "termination_grace_period": self.spec.template.termination_grace_period,
            "node_class_ref": (
                [self.spec.template.node_class_ref.group,
                 self.spec.template.node_class_ref.kind,
                 self.spec.template.node_class_ref.name]
                if self.spec.template.node_class_ref
                else None
            ),
        }
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()
        ).hexdigest()[:16]

    def allowed_disruptions_by_reason(
        self, reason: str, total_nodes: int, now: Optional[float] = None
    ) -> int:
        """Min across budgets matching the reason (nodepool.go:305-318)."""
        allowed = total_nodes
        for budget in self.spec.disruption.budgets:
            if budget.reasons and reason not in budget.reasons and REASON_ALL not in budget.reasons:
                continue
            allowed = min(allowed, budget.allowed_disruptions(total_nodes, now))
        return max(allowed, 0)
