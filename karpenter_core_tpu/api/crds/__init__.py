"""Generated CRD manifests (tools/gen_crds.py; reference pkg/apis/crds/)."""
