from karpenter_core_tpu.api import labels  # noqa: F401
