"""Pod scheduling predicates (reference: pkg/utils/pod/scheduling.go)."""
from __future__ import annotations

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import POD_FAILED, POD_SUCCEEDED, Pod


def is_scheduled(pod: Pod) -> bool:
    return bool(pod.node_name)


def is_terminal(pod: Pod) -> bool:
    return pod.phase in (POD_SUCCEEDED, POD_FAILED)


def is_terminating(pod: Pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def is_provisionable(pod: Pod) -> bool:
    """Pending, unscheduled, ungated, non-mirror (scheduling.go IsProvisionable)."""
    return (
        not is_scheduled(pod)
        and not is_terminal(pod)
        and not is_terminating(pod)
        and not pod.scheduling_gates
        and not pod.is_mirror
    )


def is_reschedulable(pod: Pod) -> bool:
    """Counts for rescheduling when its node is disrupted
    (scheduling.go IsReschedulable)."""
    return (
        not is_terminal(pod)
        and not is_terminating(pod)
        and not pod.is_daemonset
        and not pod.is_mirror
    )


def is_evictable(pod: Pod) -> bool:
    return not is_terminal(pod) and not pod.is_mirror


def is_disruptable(pod: Pod) -> bool:
    """do-not-disrupt pods block voluntary disruption (scheduling.go)."""
    return (
        pod.metadata.annotations.get(apilabels.DO_NOT_DISRUPT_ANNOTATION_KEY)
        != "true"
    )
