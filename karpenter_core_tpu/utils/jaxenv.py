"""Force a virtual multi-device CPU mesh before first JAX backend use.

One copy of the box-specific bootstrap shared by tests/conftest.py and
__graft_entry__.dryrun_multichip: this machine's axon sitecustomize imports
jax and programmatically selects the axon TPU platform at interpreter
start, so env vars alone are too late — the working override is
``jax.config.update("jax_platforms", "cpu")`` after import but before the
first backend use. XLA reads ``--xla_force_host_platform_device_count``
at backend init, which has not happened yet at that point.
"""
from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_virtual_cpu_mesh(n_devices: int) -> None:
    """Guarantee >= ``n_devices`` JAX devices on the CPU platform.

    Must run before any JAX backend use (jax.devices(), jit dispatch, ...);
    asserts loudly if the backend was already initialized on another
    platform rather than silently proceeding on it.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    if m:
        count = max(int(m.group(1)), n_devices)
        flags = flags.replace(m.group(0), f"{_COUNT_FLAG}={count}")
    else:
        flags = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # too late — the checks below report the actual state

    if jax.default_backend() != "cpu":
        raise RuntimeError(
            f"could not force the CPU platform (backend is "
            f"{jax.default_backend()!r}); force_virtual_cpu_mesh must run "
            f"before any JAX backend use"
        )
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"need {n_devices} virtual CPU devices, have {jax.devices()} — "
            f"the backend initialized before this call, so the device-count "
            f"flag could not take effect; force_virtual_cpu_mesh({n_devices}) "
            f"must run before any JAX backend use"
        )


def enable_persistent_compile_cache(path: str = ".jax_cache") -> None:
    """Point JAX's persistent compilation cache at a repo-local directory.

    The solver's cold compile is seconds of XLA work; the persistent cache
    makes it a one-time cost per (shape-bucket, jax version, chip) instead
    of per process. Safe to call multiple times; silently a no-op on jax
    builds without the cache config.
    """
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:
        pass
