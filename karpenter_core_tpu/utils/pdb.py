"""PodDisruptionBudget limits (reference: pkg/utils/pdb/pdb.go:33-118).

The reference reads ``pdb.Status.DisruptionsAllowed`` maintained by the
kube-controller-manager's disruption controller; this framework has no such
controller, so ``Limits`` computes the same quantity from live pods at
build time: allowed = healthy − desiredHealthy, with desiredHealthy from
minAvailable or maxUnavailable; percentages round up in both cases
(GetScaledValueFromIntOrPercent(..., roundUp=true) in the policy/v1
disruption controller).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from karpenter_core_tpu.api.objects import (
    POD_FAILED,
    POD_RUNNING,
    POD_SUCCEEDED,
    Pod,
    PodDisruptionBudget,
)
from karpenter_core_tpu.utils import pod as podutil


def _resolve(value, expected: int, round_up: bool) -> int:
    if isinstance(value, str) and value.endswith("%"):
        # exact integer arithmetic like intstr.GetScaledValueFromIntOrPercent
        # — float math is off by one for pairs like 14% of 50
        num = int(value[:-1])
        if round_up:
            return -(-num * expected // 100)
        return num * expected // 100
    return int(value)


@dataclass
class _PdbItem:
    key: str
    namespace: str
    selector: object
    disruptions_allowed: int
    can_always_evict_unhealthy: bool


class Limits:
    """Evaluate whether a pod list is evictable (pdb.go:54-89)."""

    def __init__(self, items: List[_PdbItem]):
        self.items = items

    @classmethod
    def from_kube(cls, kube) -> "Limits":
        pods = [
            p
            for p in kube.list_pods()
            if p.phase not in (POD_SUCCEEDED, POD_FAILED)
            and p.metadata.deletion_timestamp is None
        ]
        items = []
        for pdb in kube.list_pdbs():
            if pdb.selector is None:
                continue
            matching = [
                p
                for p in pods
                if p.metadata.namespace == pdb.metadata.namespace
                and pdb.selector.matches(p.metadata.labels)
            ]
            expected = len(matching)
            healthy = sum(1 for p in matching if p.phase == POD_RUNNING)
            if pdb.min_available is not None:
                desired = _resolve(pdb.min_available, expected, round_up=True)
            elif pdb.max_unavailable is not None:
                desired = expected - _resolve(
                    pdb.max_unavailable, expected, round_up=True
                )
            else:
                desired = expected
            items.append(
                _PdbItem(
                    key=pdb.key(),
                    namespace=pdb.metadata.namespace,
                    selector=pdb.selector,
                    disruptions_allowed=max(healthy - desired, 0),
                    can_always_evict_unhealthy=(
                        pdb.unhealthy_pod_eviction_policy == "AlwaysAllow"
                    ),
                )
            )
        return cls(items)

    def blocking_pdb(self, pod: Pod) -> Optional[str]:
        """PDB key that blocks evicting this single pod, if any."""
        if not podutil.is_evictable(pod):
            return None
        for item in self.items:
            if item.namespace != pod.metadata.namespace:
                continue
            if not item.selector.matches(pod.metadata.labels):
                continue
            if item.can_always_evict_unhealthy and pod.phase != POD_RUNNING:
                continue
            if item.disruptions_allowed == 0:
                return item.key
        return None

    def can_evict_pods(self, pods: List[Pod]) -> Optional[str]:
        """Error string naming the first fully-blocking PDB (pdb.go:56-89:
        every pod must be individually evictable; simultaneity is handled
        by the eviction queue's retries). Non-evictable pods (mirror,
        terminal) are skipped inside blocking_pdb, so a PDB matching only
        them does not block (pdb.go:58-62)."""
        for pod in pods:
            key = self.blocking_pdb(pod)
            if key is not None:
                return f"pdb {key} prevents pod evictions"
        return None
