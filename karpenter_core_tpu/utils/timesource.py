"""Process-wide time source for object timestamps.

The reference gets testable time by injecting k8s.io/utils/clock into every
controller AND running envtest with real wall-clock objects. Our dataclass
defaults (ObjectMeta.creation_timestamp, Condition.last_transition_time)
need a seam instead: the Operator points this module at its clock so fake
clocks drive every timestamp consistently."""
from __future__ import annotations

import time
from typing import Callable

_now: Callable[[], float] = time.time


def now() -> float:
    return _now()


def set_source(fn: Callable[[], float]) -> None:
    global _now
    _now = fn
