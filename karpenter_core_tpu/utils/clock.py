"""Injectable clock (the reference uses k8s.io/utils/clock everywhere so
TTL/window logic is testable; FakeClock mirrors clock/testing)."""
from __future__ import annotations

import time


class Clock:
    def now(self) -> float:
        return time.time()

    def since(self, t: float) -> float:
        return self.now() - t


class FakeClock(Clock):
    def __init__(self, start: float = 1_000_000.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def step(self, seconds: float) -> None:
        self._now += seconds

    def set(self, t: float) -> None:
        self._now = t
