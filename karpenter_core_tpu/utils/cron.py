"""Minimal 5-field cron matching for disruption budget windows
(reference: nodepool.go:353-367 uses robfig/cron)."""
from __future__ import annotations

import time
from typing import Optional


def _parse_field(field: str, lo: int, hi: int) -> set:
    out = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            rng = range(lo, hi + 1)
        elif "-" in part:
            a, b = part.split("-", 1)
            rng = range(int(a), int(b) + 1)
        else:
            rng = range(int(part), int(part) + 1)
        out.update(v for v in rng if (v - lo) % step == 0 or step == 1)
    return out


def matches(expr: str, ts: float) -> bool:
    """True if the cron expression fires at the minute containing ts (UTC)."""
    fields = expr.split()
    if fields and fields[0].startswith("@"):
        expr = {"@daily": "0 0 * * *", "@hourly": "0 * * * *",
                "@weekly": "0 0 * * 0", "@monthly": "0 0 1 * *"}.get(fields[0], expr)
        fields = expr.split()
    if len(fields) != 5:
        raise ValueError(f"cannot parse cron expression {expr!r}")
    minute, hour, dom, month, dow = fields
    tm = time.gmtime(ts)
    return (
        tm.tm_min in _parse_field(minute, 0, 59)
        and tm.tm_hour in _parse_field(hour, 0, 23)
        and tm.tm_mday in _parse_field(dom, 1, 31)
        and tm.tm_mon in _parse_field(month, 1, 12)
        and (tm.tm_wday + 1) % 7 in _parse_field(dow, 0, 6)
    )


def last_fire_before(expr: str, now: float, horizon_days: int = 35) -> Optional[float]:
    """Most recent fire time <= now, scanned minute-wise back over the horizon."""
    minute = int(now // 60) * 60
    for _ in range(horizon_days * 24 * 60):
        if matches(expr, minute):
            return float(minute)
        minute -= 60
    return None
