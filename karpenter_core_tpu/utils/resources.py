"""Resource-list arithmetic (reference: pkg/utils/resources/resources.go).

ResourceLists are plain dict[str, float]; missing keys read as zero, matching
apimachinery Quantity map semantics.
"""
from __future__ import annotations

from typing import Iterable

from karpenter_core_tpu.api.objects import RESOURCE_PODS, Pod


def merge(*lists: dict) -> dict:
    """Sum resource lists (resources.go:50-63)."""
    out: dict = {}
    for rl in lists:
        for name, qty in rl.items():
            out[name] = out.get(name, 0.0) + qty
    return out


def merge_into(dest: dict, src: dict) -> dict:
    """In-place sum (resources.go:68-79)."""
    for name, qty in src.items():
        dest[name] = dest.get(name, 0.0) + qty
    return dest


def subtract(lhs: dict, rhs: dict) -> dict:
    """lhs - rhs over lhs's keys (resources.go:81-93)."""
    out = dict(lhs)
    for name in lhs:
        out[name] = lhs[name] - rhs.get(name, 0.0)
    return out


def requests_for_pods(*pods: Pod) -> dict:
    """Total requests plus the implicit 'pods' count resource
    (resources.go:28-37)."""
    out = merge(*(p.resource_requests for p in pods))
    out[RESOURCE_PODS] = out.get(RESOURCE_PODS, 0.0) + float(len(pods))
    return out


def merge_repeated(dest: dict, src: dict, k: int) -> dict:
    """dest folded with src k times by repeated addition, NOT dest + k*src:
    group-add paths must land on the same float64 sums the sequential
    merge-per-pod path produces, or exact-boundary fits flake between the
    two."""
    out = dict(dest)
    for _ in range(int(k)):
        for name, qty in src.items():
            out[name] = out.get(name, 0.0) + qty
    return out


def fits(candidate: dict, total: dict) -> bool:
    """candidate <= total pointwise; any negative total never fits
    (resources.go:217-231)."""
    if any_negative(total):
        return False
    return all(qty <= total.get(name, 0.0) for name, qty in candidate.items())


def merge_limits_into_requests(container) -> dict:
    """A container's effective requests: explicit requests, with limits
    standing in for any resource that has a limit but no request
    (resources.go:185-197 MergeResourceLimitsIntoRequests)."""
    out = dict(container.resource_requests)
    for name, qty in container.resource_limits.items():
        if name not in container.resource_requests:
            out[name] = qty
    return out


def _pod_aggregate(pod, container_reqs) -> dict:
    """Shared shape of podRequests/podLimits (resources.go:96-162): sum the
    regular containers plus restartable (sidecar) init containers, then max
    against each non-restartable init container's needs stacked on the
    sidecars started before it."""
    from karpenter_core_tpu.api.objects import CONTAINER_RESTART_ALWAYS

    total: dict = {}
    restartable: dict = {}
    max_init: dict = {}
    for c in pod.containers:
        merge_into(total, container_reqs(c))
    for c in pod.init_containers:
        reqs = container_reqs(c)
        if c.restart_policy == CONTAINER_RESTART_ALWAYS:
            merge_into(total, reqs)
            merge_into(restartable, reqs)
            max_init = cmp_max(max_init, restartable)
        else:
            max_init = cmp_max(max_init, merge(reqs, restartable))
    total = cmp_max(total, max_init)
    if pod.overhead:
        merge_into(total, pod.overhead)
    return total


def pod_requests(pod) -> dict:
    """Aggregate pod requests from container specs (resources.go:96-128)."""
    return _pod_aggregate(pod, merge_limits_into_requests)


def pod_limits(pod) -> dict:
    """Aggregate pod limits from container specs (resources.go:131-162).
    Limits do NOT fall back to requests — only explicit limits count."""
    return _pod_aggregate(pod, lambda c: dict(c.resource_limits))


def ceiling(pod) -> tuple:
    """(requests, limits) for the pod (resources.go:164-169 Ceiling)."""
    return pod_requests(pod), pod_limits(pod)


def limits_for_pods(*pods: Pod) -> dict:
    """Total limits plus the implicit 'pods' count resource
    (resources.go:39-47); pods built from container specs carry derived
    limits, flat-request pods count as zero-limit."""
    out = merge(*(p.resource_limits for p in pods))
    out[RESOURCE_PODS] = out.get(RESOURCE_PODS, 0.0) + float(len(pods))
    return out


def cmp_max(*lists: dict) -> dict:
    """Pointwise max (resources.go MaxResources)."""
    out: dict = {}
    for rl in lists:
        for name, qty in rl.items():
            if qty > out.get(name, float("-inf")):
                out[name] = qty
    return out


def any_negative(rl: dict) -> bool:
    return any(q < 0 for q in rl.values())


def is_zero(rl: dict) -> bool:
    return all(q == 0 for q in rl.values())


def to_string(rl: dict) -> str:
    if not rl:
        return "{}"
    return ", ".join(f"{k}={v:g}" for k, v in sorted(rl.items()))
