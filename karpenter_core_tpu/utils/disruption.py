"""Disruption cost model (reference: pkg/utils/disruption/disruption.go:37-79).

Also the single home of the PRIORITY TIER ordering: the gangsched kernel
(ops/gangsched.py) packs tiers high→low and treats only strictly-lower
tiers as evictable, the host tiered-greedy fallback (solver/gangs.py)
bands by the same value, and the verifier's preemption-legality check
(solver/verify.py) compares the same value — one function, three readers,
so the orderings can never drift apart.
"""
from __future__ import annotations

from typing import List

from karpenter_core_tpu.api.objects import Pod

POD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"

# int32 bounds: tiers ride device tensors (ops/gangsched ev_tier planes)
_TIER_MAX = 2**31 - 1
_TIER_MIN = -(2**31 - 1)


def priority_tier(priority) -> int:
    """The canonical scheduling tier of a PriorityClass value: the value
    itself, clamped to int32 (kube PriorityClass values are int32 anyway —
    system-cluster-critical is 2e9). Unset/garbage → tier 0, the k8s
    default priority."""
    try:
        p = int(priority or 0)
    except (TypeError, ValueError):
        return 0
    return max(_TIER_MIN, min(p, _TIER_MAX))


def lifetime_remaining(clock, nodepool, node_claim) -> float:
    """Fraction of node lifetime left in [0,1]; expiring-soon nodes are
    cheaper to disrupt (disruption.go:37-47)."""
    expire = node_claim.spec.expire_after.seconds
    if expire is None or expire <= 0:
        return 1.0
    age = clock.since(node_claim.metadata.creation_timestamp)
    return min(max((expire - age) / expire, 0.0), 1.0)


def eviction_cost(pod: Pod) -> float:
    """Base 1.0 + deletion-cost/2^27 + priority/2^25, clamped to [-10, 10]
    (disruption.go:49-70).

    EACH TERM clamps before the total clamp — deletion to ±1, priority to
    ±8 — so base + both extremes spans [-8, 10] and the total clamp is a
    backstop the interior never touches. The raw reference arithmetic let
    priority/2^25 saturate the documented [-10, 10] contract for any
    PriorityClass ≥ ~3.0e8 (system-cluster-critical is 2e9 → 59.6),
    erasing the deletion-cost ordering among all critical pods; a single
    ±9 priority clamp still parked critical pods at the 10.0 ceiling
    (1 + 9), erasing POSITIVE deletion costs. With per-term bounds both
    orderings stay live across each term's documented scale.
    Tier ORDERING (which pod may evict which) never rides this cost; that
    is priority_tier's job — this cost only ranks eviction victims within
    a legal (strictly-lower) tier."""
    cost = 1.0
    raw = pod.metadata.annotations.get(POD_DELETION_COST_ANNOTATION)
    if raw is not None:
        try:
            term = float(raw) / 2.0**27
            cost += min(max(term, -1.0), 1.0)
        except ValueError:
            pass
    if pod.priority:
        term = float(priority_tier(pod.priority)) / 2.0**25
        cost += min(max(term, -8.0), 8.0)
    return min(max(cost, -10.0), 10.0)


def rescheduling_cost(pods: List[Pod]) -> float:
    return sum(eviction_cost(p) for p in pods)
