"""Disruption cost model (reference: pkg/utils/disruption/disruption.go:37-79)."""
from __future__ import annotations

from typing import List

from karpenter_core_tpu.api.objects import Pod

POD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"


def lifetime_remaining(clock, nodepool, node_claim) -> float:
    """Fraction of node lifetime left in [0,1]; expiring-soon nodes are
    cheaper to disrupt (disruption.go:37-47)."""
    expire = node_claim.spec.expire_after.seconds
    if expire is None or expire <= 0:
        return 1.0
    age = clock.since(node_claim.metadata.creation_timestamp)
    return min(max((expire - age) / expire, 0.0), 1.0)


def eviction_cost(pod: Pod) -> float:
    """Base 1.0 + deletion-cost/2^27 + priority/2^25, clamped to [-10, 10]
    (disruption.go:49-70)."""
    cost = 1.0
    raw = pod.metadata.annotations.get(POD_DELETION_COST_ANNOTATION)
    if raw is not None:
        try:
            cost += float(raw) / 2.0**27
        except ValueError:
            pass
    if pod.priority:
        cost += float(pod.priority) / 2.0**25
    return min(max(cost, -10.0), 10.0)


def rescheduling_cost(pods: List[Pod]) -> float:
    return sum(eviction_cost(p) for p in pods)
