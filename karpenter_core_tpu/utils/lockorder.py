"""Opt-in runtime lock-order witness (``GRAFT_LOCK_WITNESS=1``).

graftlint's GL701 derives the solver tier's acquired-while-held graph
statically (tools/graftlint/dataflow.LockDataflow); this shim records
the graph that ACTUALLY happens at runtime, so a chaos soak can assert
the dynamic view stays inside the static one — the two cannot drift
without a test failing. The tier's current static graph has no edges at
all (one lock at a time, by design), which makes the soak's assertion
maximally strict: any runtime nesting of two witnessed locks is a
finding.

Zero-cost when disarmed: production code never imports this module; the
soak (tests/test_lockorder_witness.py) wraps lock attributes on live
objects explicitly via :func:`wrap`, and ``maybe_wrap`` is a no-op
unless the environment opts in.

Lock ids use GL701's identity scheme — ``"ClassName.attr"`` — so the
observed edges compare directly against
``dataflow.get_locks(files).order_edges``.
"""
from __future__ import annotations

import os
import threading
from typing import Iterable, Optional, Set, Tuple

ENV_FLAG = "GRAFT_LOCK_WITNESS"


def enabled() -> bool:
    return os.environ.get(ENV_FLAG, "") == "1"


class LockWitness:
    """Per-thread held stacks, one process-global edge set.

    ``edges`` accumulates every (held_id, acquired_id) pair observed:
    thread T acquired the second lock while still holding the first.
    Re-entrant re-acquisition of the same id records nothing (RLock
    helpers are the tier's designed idiom, and GL701 skips them too).
    """

    def __init__(self) -> None:
        self._tls = threading.local()
        self._edges_lock = threading.Lock()
        self.edges: Set[Tuple[str, str]] = set()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def acquired(self, lock_id: str) -> None:
        st = self._stack()
        new = [
            (held, lock_id) for held in set(st)
            if held != lock_id and (held, lock_id) not in self.edges
        ]
        if new:
            with self._edges_lock:
                self.edges.update(new)
        st.append(lock_id)

    def released(self, lock_id: str) -> None:
        st = self._stack()
        # drop the most recent acquisition of this id (LIFO discipline,
        # tolerant of out-of-order releases from acquire/release pairs)
        for i in range(len(st) - 1, -1, -1):
            if st[i] == lock_id:
                del st[i]
                break

    def reset(self) -> None:
        with self._edges_lock:
            self.edges.clear()

    def assert_within(self, static_edges: Iterable[Tuple[str, str]]) -> None:
        """Every observed edge must exist in the static GL701 graph."""
        allowed = set(static_edges)
        stray = sorted(e for e in self.edges if e not in allowed)
        if stray:
            lines = "\n".join(f"  {s} -> {d}" for s, d in stray)
            raise AssertionError(
                "runtime lock acquisitions outside the static lock-order"
                f" graph:\n{lines}\n"
                "either the code grew a nesting GL701 cannot see (fix the"
                " static domain) or a genuinely new nesting shipped (run"
                " graftlint and fix the order)"
            )


_WITNESS = LockWitness()


def witness() -> LockWitness:
    """The process-global witness the wrappers report to by default."""
    return _WITNESS


class WitnessedLock:
    """A Lock/RLock/Condition proxy that reports acquisition order.

    Forwards everything else untouched, so ``with``, ``acquire(timeout=)``
    and Condition methods behave identically to the wrapped primitive.
    """

    def __init__(
        self,
        inner,
        lock_id: str,
        witness_obj: Optional[LockWitness] = None,
    ) -> None:
        self._inner = inner
        self._id = lock_id
        self._witness = witness_obj if witness_obj is not None else _WITNESS

    def acquire(self, *args, **kwargs) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._witness.acquired(self._id)
        return got

    def release(self) -> None:
        self._witness.released(self._id)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __getattr__(self, name):
        # Condition.wait/notify and friends pass straight through
        return getattr(self._inner, name)


def wrap(
    obj,
    attr: str,
    lock_id: str,
    witness_obj: Optional[LockWitness] = None,
) -> WitnessedLock:
    """Swap ``obj.<attr>`` for a witnessed proxy and return it.

    Unconditional — the soak calls this explicitly on the objects it
    drives. ``lock_id`` must use GL701's "ClassName.attr" identity so
    observed edges compare against the static graph.
    """
    proxy = WitnessedLock(getattr(obj, attr), lock_id, witness_obj)
    setattr(obj, attr, proxy)
    return proxy


def maybe_wrap(
    obj,
    attr: str,
    lock_id: str,
    witness_obj: Optional[LockWitness] = None,
):
    """:func:`wrap`, gated on ``GRAFT_LOCK_WITNESS=1`` — safe to sprinkle
    into debug/soak harness setup paths."""
    if not enabled():
        return getattr(obj, attr)
    return wrap(obj, attr, lock_id, witness_obj)
