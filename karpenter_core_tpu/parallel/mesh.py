"""Mesh construction and sharding specs for the FFD solve.

One copy of the "leading axis == n_slots -> shard over 'slots', else
replicate" rule, shared by the driver entry (__graft_entry__.py), the
sharded-parity tests, and any multi-chip deployment of the solver.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def slot_mesh(n_devices: int, axis: str = "slots") -> Mesh:
    """1-D mesh over the first n_devices JAX devices."""
    devices = jax.devices()
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devices)} ({devices})"
        )
    return Mesh(np.array(devices[:n_devices]), (axis,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def slot_shardings(mesh: Mesh, state, n_slots: int, axis: str = "slots"):
    """Shardings pytree for a SlotState: leaves leading with the slot axis
    (dim 0 == n_slots) shard over the mesh; scalars/others replicate."""

    def spec(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] == n_slots:
            return NamedSharding(mesh, P(axis, *([None] * (leaf.ndim - 1))))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec, state)


def batch_sharding(mesh: Mesh, ndim: int, axis: str = "slots") -> NamedSharding:
    """Shard a batch-leading array (e.g. the consolidation prefix axis)."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))
