"""Mesh construction and sharding specs for the FFD solve.

One copy of the slot-axis sharding rules, shared by the production solve
path (models/provisioner.DeviceScheduler with ``devices > 1``), the driver
entry (__graft_entry__.py), the sharded-parity tests, and the solverd
sidecar (``--devices``).

The SlotState sharding is matched BY FIELD NAME (``SLOT_STATE_SPECS``),
not by a "leading dim == n_slots" shape heuristic: a non-slot array whose
leading dimension coincidentally equals n_slots (e.g. a [Gz, V] zcount on
a solve with Gz == n_slots) must replicate, and a new SlotState field must
be classified here explicitly — ``slot_shardings`` refuses to guess.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Explicit slot-axis annotation for ops/ffd.SlotState: field -> the dim
# carrying the slot axis (sharded over the mesh), or None (replicated).
# zcount is [Gz, V] label-group count state and the head scalars ride the
# scan carry on every device; everything else leads with [N, ...].
# Field-set parity with the SlotState definition is machine-checked at
# edit time (graftlint GL502) on top of the runtime raise below.
SLOT_STATE_SPECS = {
    "valmask": 0,
    "defines": 0,
    "complement": 0,
    "negative": 0,
    "gt": 0,
    "lt": 0,
    "itmask": 0,
    "requests": 0,
    "capacity": 0,
    "kind": 0,
    "template": 0,
    "podcount": 0,
    "hcount": 0,
    "zcount": None,
    "next_free": None,
    "overflow": None,
    "carry": None,
}


def slot_mesh(n_devices: int, axis: str = "slots") -> Mesh:
    """1-D mesh over the first n_devices JAX devices."""
    devices = jax.devices()
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devices)} ({devices})"
        )
    return Mesh(np.array(devices[:n_devices]), (axis,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def axis_sharding(
    mesh: Mesh, ndim: int, dim: int = 0, axis: str = "slots"
) -> NamedSharding:
    """Shard one dimension of an ndim-array over the mesh axis."""
    spec = [None] * ndim
    spec[dim] = axis
    return NamedSharding(mesh, P(*spec))


def slot_shardings(mesh: Mesh, state, n_slots: int, axis: str = "slots"):
    """Shardings pytree for a SlotState: slot-axis leaves (annotated in
    SLOT_STATE_SPECS) shard over the mesh; everything else replicates.

    For a NamedTuple state every field must be classified — an unlisted
    field raises rather than falling back to a shape guess, so adding a
    SlotState field forces a sharding decision here. Slot-axis fields are
    additionally validated against ``n_slots`` (a mis-sized state is a
    caller bug, not something to shard anyway). Non-NamedTuple pytrees
    (ad-hoc test trees) keep the legacy leading-dim heuristic.
    """
    if hasattr(state, "_fields"):
        unknown = [f for f in state._fields if f not in SLOT_STATE_SPECS]
        if unknown:
            raise ValueError(
                f"slot_shardings: unclassified SlotState field(s) {unknown};"
                " annotate them in parallel.mesh.SLOT_STATE_SPECS"
            )
        specs = {}
        for f in state._fields:
            leaf = getattr(state, f)
            dim = SLOT_STATE_SPECS[f]
            if dim is None:
                specs[f] = replicated(mesh)
            else:
                if leaf.shape[dim] != n_slots:
                    raise ValueError(
                        f"slot_shardings: {f} has shape {leaf.shape}, "
                        f"expected dim {dim} == n_slots ({n_slots})"
                    )
                specs[f] = axis_sharding(mesh, leaf.ndim, dim, axis)
        return type(state)(**specs)

    def spec(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] == n_slots:
            return axis_sharding(mesh, leaf.ndim, 0, axis)
        return replicated(mesh)

    return jax.tree.map(spec, state)


def batch_sharding(mesh: Mesh, ndim: int, axis: str = "slots") -> NamedSharding:
    """Shard a batch-leading array (e.g. the consolidation prefix axis)."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


# The ClassStep fields carrying a slot axis (their unbatched dim index):
# exist_taint_ok is the scanned [J, N] per-class taint-tolerance plane and
# topo_rank the scanned [J, N] network-distance-level plane (topoaware,
# ISSUE 20 — often the leafless None default, which shards as nothing);
# every other field is per-class metadata and replicates. Kept here beside
# SLOT_STATE_SPECS so the batched placement below classifies BOTH scanned
# pytrees by field name instead of shape guessing.
CLASS_STEP_SPECS = {"exist_taint_ok": 1, "topo_rank": 1}

# ops/gangsched.EvPlanes — the preemption pass's evictable-capacity planes.
# Every field leads with the slot axis ([N, P] / [N, P, R]): each slot's
# evictable bound pods are that slot's private state, so they shard over
# the mesh exactly like SlotState planes. Same refuse-to-guess contract:
# a new EvPlanes field must be classified here (gang_plane_shardings
# raises on an unlisted field).
GANG_EV_SPECS = {"req": 0, "tier": 0, "cost": 0, "valid": 0}


def gang_plane_shardings(mesh: Mesh, planes, n_slots: int,
                         axis: str = "slots"):
    """Shardings for an ops/gangsched.EvPlanes: slot axis sharded over the
    mesh, classified by field name via GANG_EV_SPECS — the gangsched twin
    of slot_shardings (and the placement route graftlint GL501 resolves
    for the gang-state jit entries)."""
    unknown = [f for f in planes._fields if f not in GANG_EV_SPECS]
    if unknown:
        raise ValueError(
            f"gang_plane_shardings: unclassified EvPlanes field(s)"
            f" {unknown}; annotate them in parallel.mesh.GANG_EV_SPECS"
        )
    specs = {}
    for f in planes._fields:
        leaf = getattr(planes, f)
        dim = GANG_EV_SPECS[f]
        if leaf.shape[dim] != n_slots:
            raise ValueError(
                f"gang_plane_shardings: {f} has shape {leaf.shape},"
                f" expected dim {dim} == n_slots ({n_slots})"
            )
        specs[f] = axis_sharding(mesh, leaf.ndim, dim, axis)
    return type(planes)(**specs)


def batched_gang_plane_shardings(mesh: Mesh, planes, n_slots: int,
                                 axis: str = "slots"):
    """Problem-batched EvPlanes ([B, N, ...] leaves): batch axis
    replicated, slot axis sharded — composes with the continuous-batching
    vmapped gang solve the same way batched_slot_shardings does."""
    return _batched_specs(mesh, planes, GANG_EV_SPECS, n_slots, axis)


def topo_plane_shardings(mesh: Mesh, tree, n_slots: int,
                         axis: str = "slots"):
    """Shardings for the topoaware hop planes (ops/topoplan → the gang
    classes' [J, N] topo_rank rows): the trailing slot axis shards over
    the mesh, leading dims replicate — the planes ride the same scan as
    exist_taint_ok, so they must land sharded the same way. A leaf whose
    trailing dim is not the slot axis is a caller bug and raises (the
    refuse-to-guess contract)."""
    def spec(leaf):
        if leaf.shape[-1] != n_slots:
            raise ValueError(
                f"topo_plane_shardings: leaf has shape {leaf.shape},"
                f" expected trailing dim == n_slots ({n_slots})"
            )
        return axis_sharding(mesh, leaf.ndim, leaf.ndim - 1, axis)

    return jax.tree.map(spec, tree)


def relax_plane_shardings(mesh: Mesh, tree):
    """Shardings for the relaxsolve assignment planes (ops/relax.py): the
    [C, S]/[C] class×template tensors carry NO slot axis — they replicate
    across the mesh (tiny next to the slot planes), so the relax_choose
    dispatch composes with the pjit-over-slots solve path without a
    resharding hop. Kept as an explicit parallel.mesh route (rather than
    bare device_put) so graftlint GL501/GL503 resolve the relax entries'
    placement the same way they resolve every other kernel family's."""
    repl = replicated(mesh)
    return jax.tree.map(lambda _: repl, tree)


def pallas_slot_shardings(mesh: Mesh, tree):
    """Shardings for trees bound for the Pallas fused kernels
    (ops/pallas_ffd.py) on a multi-device mesh: EVERY leaf replicates.

    The pallas_call boundary is opaque to the GSPMD partitioner — it
    cannot split the fused per-class step over the slot axis the way it
    splits the XLA ops — so a pallas dispatch consumes whole planes on
    every device. Committing them replicated up front (rather than
    letting XLA insert an all-gather per dispatch against slot-sharded
    inputs) makes that cost explicit and deterministic, and keeps the
    placement on a sanctioned parallel.mesh route so graftlint
    GL501/GL503 resolve the pallas jit entries' slot-state placement
    exactly like every other kernel family's. Results stay
    byte-identical to the sharded XLA path; multi-device THROUGHPUT is
    the XLA backend's job (bench cfg8), single-core fusion is this
    one's (bench cfg17)."""
    repl = replicated(mesh)
    return jax.tree.map(lambda _: repl, tree)


def _batched_specs(mesh: Mesh, tree, table: dict, n_slots: int, axis: str):
    """Shardings for a problem-batched NamedTuple [B, ...]: the batch axis
    replicates (each device holds every problem's shard — the vmap then
    composes with the slot-axis pjit unchanged), slot dims shift +1 past
    the leading batch axis, everything else replicates. Same refuse-to-
    guess contract as slot_shardings: an unclassified field raises."""
    unknown = [f for f in tree._fields if f not in table]
    if unknown:
        raise ValueError(
            f"batched specs: unclassified {type(tree).__name__} field(s)"
            f" {unknown}; annotate them in parallel.mesh"
        )
    specs = {}
    for f in tree._fields:
        leaf = getattr(tree, f)
        if leaf is None:
            # a leafless optional plane (ClassStep.topo_rank default):
            # None in the value tree must pair with None in the spec tree
            specs[f] = None
            continue
        dim = table[f]
        if dim is None:
            specs[f] = replicated(mesh)
        else:
            bdim = dim + 1  # past the leading problem axis
            if leaf.shape[bdim] != n_slots:
                raise ValueError(
                    f"batched specs: {f} has shape {leaf.shape}, expected"
                    f" dim {bdim} == n_slots ({n_slots})"
                )
            specs[f] = axis_sharding(mesh, leaf.ndim, bdim, axis)
    return type(tree)(**specs)


def batched_slot_shardings(mesh: Mesh, state, n_slots: int,
                           axis: str = "slots"):
    """Shardings for a problem-batched SlotState ([B, N, ...] leaves):
    batch axis replicated, slot axis sharded over the mesh — the batched
    twin of slot_shardings, classified by the same SLOT_STATE_SPECS."""
    return _batched_specs(mesh, state, SLOT_STATE_SPECS, n_slots, axis)


def batched_step_shardings(mesh: Mesh, steps, n_slots: int,
                           axis: str = "slots"):
    """Shardings for a problem-batched ClassStep ([B, J, ...] leaves):
    only exist_taint_ok carries the slot axis (dim 2 once batched)."""
    table = {f: CLASS_STEP_SPECS.get(f) for f in steps._fields}
    return _batched_specs(mesh, steps, table, n_slots, axis)


def resolve_devices(requested) -> int:
    """Resolve a device-count request against the local platform.

    ``1`` (the default everywhere) short-circuits without touching the
    backend — constructing a single-device scheduler must not initialize
    XLA early. ``0``/None means "all local devices"; any other request
    clamps to what exists, so an 8-device config degrades to the
    single-device path on a 1-chip box instead of crashing.
    """
    requested = int(requested or 0)
    if requested == 1:
        return 1
    available = len(jax.devices())
    if requested <= 0:
        return available
    return max(1, min(requested, available))


def pad_to_devices(n: int, n_devices: int) -> int:
    """Round n up to a multiple of n_devices: ``device_put`` over the slot
    axis needs even division, and padded slots are inert by construction
    (kind=0 never takes — the slot-axis-invariance parity property)."""
    if n_devices <= 1:
        return n
    return -(-n // n_devices) * n_devices
