"""Device mesh + sharding of the solve across ICI.

The solve's data-parallel axis is the slot axis (candidate nodes /
in-flight claims): feasibility masks, capacity arithmetic, and the
requirement-state merge are embarrassingly parallel across slots, while
the first-fit prefix sum (an int32 cumsum — exact under any reduction
order) and the class scan are handled by XLA collectives. Consolidation's
prefix sweep adds a second, fully independent batch axis (the candidate
prefix), sharded the same way.

This is the PRODUCTION scale axis, not a dry-run helper: a
``DeviceScheduler(devices=N)`` places SlotState pre-sharded over the mesh
(``slot_shardings`` — explicit field-name annotation, see mesh.py) and the
jit'd kernels compile SPMD from the argument shardings.
"""
from karpenter_core_tpu.parallel.mesh import (
    CLASS_STEP_SPECS,
    SLOT_STATE_SPECS,
    axis_sharding,
    batch_sharding,
    batched_slot_shardings,
    batched_step_shardings,
    pad_to_devices,
    replicated,
    resolve_devices,
    slot_mesh,
    slot_shardings,
)

__all__ = [
    "CLASS_STEP_SPECS",
    "SLOT_STATE_SPECS",
    "axis_sharding",
    "batch_sharding",
    "batched_slot_shardings",
    "batched_step_shardings",
    "pad_to_devices",
    "replicated",
    "resolve_devices",
    "slot_mesh",
    "slot_shardings",
]
