"""Device mesh + sharding of the solve across ICI.

The solve's data-parallel axis is the slot axis (candidate nodes /
in-flight claims): feasibility masks, capacity arithmetic, and the
requirement-state merge are embarrassingly parallel across slots, while
the first-fit prefix sum (an int32 cumsum — exact under any reduction
order) and the class scan are handled by XLA collectives. Consolidation's
prefix sweep adds a second, fully independent batch axis (the candidate
prefix), sharded the same way.
"""
from karpenter_core_tpu.parallel.mesh import (
    batch_sharding,
    replicated,
    slot_mesh,
    slot_shardings,
)

__all__ = ["batch_sharding", "replicated", "slot_mesh", "slot_shardings"]
