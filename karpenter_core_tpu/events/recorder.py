"""Event recorder with dedupe + rate limiting
(reference: pkg/events/recorder.go:30-100)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

DEDUPE_TTL = 120.0  # 2-minute dedupe cache (recorder.go:35)
RATE_LIMIT_QPS = 10.0


@dataclass
class Event:
    involved_object: str  # "Kind/name"
    type: str  # Normal | Warning
    reason: str
    message: str
    timestamp: float = 0.0

    def dedupe_key(self) -> tuple:
        return (self.involved_object, self.type, self.reason, self.message)


class Recorder:
    """In-memory recorder: events land in .events (the store's apiserver
    role); duplicates within the TTL are dropped, and per-reason token
    buckets cap the flow like the reference's flowcontrol limiter."""

    def __init__(self, clock):
        self.clock = clock
        self.events: List[Event] = []
        self._seen: Dict[tuple, float] = {}
        self._bucket: Dict[str, float] = {}
        self._bucket_t: Dict[str, float] = {}

    def publish(self, *events: Event) -> None:
        for e in events:
            now = self.clock.now()
            e.timestamp = now
            key = e.dedupe_key()
            last = self._seen.get(key)
            if last is not None and now - last < DEDUPE_TTL:
                continue
            if not self._allow(e.reason, now):
                continue
            self._seen[key] = now
            self.events.append(e)
            if len(self._seen) > 4096:
                self._seen = {
                    k: t
                    for k, t in self._seen.items()
                    if now - t < DEDUPE_TTL
                }

    def _allow(self, reason: str, now: float) -> bool:
        tokens = self._bucket.get(reason, RATE_LIMIT_QPS)
        tokens = min(
            RATE_LIMIT_QPS,
            tokens + (now - self._bucket_t.get(reason, now)) * RATE_LIMIT_QPS,
        )
        if tokens < 1.0:
            self._bucket[reason] = tokens
            self._bucket_t[reason] = now
            return False
        self._bucket[reason] = tokens - 1.0
        self._bucket_t[reason] = now
        return True

    def with_reason(self, reason: str) -> List[Event]:
        return [e for e in self.events if e.reason == reason]
