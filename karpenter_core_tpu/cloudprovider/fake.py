"""Unit-test cloud provider double (reference: pkg/cloudprovider/fake/cloudprovider.go:45-66,
fake/instancetype.go:180): records calls, injectable errors, settable
instance types per pool, and a synthetic n-type generator."""
from __future__ import annotations

import itertools
from typing import List, Optional

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodeclaim import COND_LAUNCHED, NodeClaim
from karpenter_core_tpu.api.objects import (
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
)
from karpenter_core_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    Offering,
    Offerings,
)
from karpenter_core_tpu.scheduling import Requirement, Requirements

GIB = 2.0**30


def fake_instance_types(n: int, zones: Optional[List[str]] = None) -> List[InstanceType]:
    """n synthetic types with exponentially-growing shapes
    (fake/instancetype.go:180)."""
    zones = zones or ["test-zone-1", "test-zone-2", "test-zone-3"]
    out = []
    for i in range(n):
        cpu = 2 ** (i % 8)
        mem = cpu * 4 * GIB
        name = f"fake-it-{i}-{cpu}cpu"
        price = 0.01 * cpu * (1 + 0.1 * (i % 3))
        offerings = Offerings(
            Offering(
                requirements=Requirements(
                    [
                        Requirement.new(apilabels.CAPACITY_TYPE_LABEL_KEY, "In", [ct]),
                        Requirement.new(apilabels.LABEL_TOPOLOGY_ZONE, "In", [z]),
                    ]
                ),
                price=price * (0.7 if ct == apilabels.CAPACITY_TYPE_SPOT else 1.0),
                available=True,
            )
            for z in zones
            for ct in (apilabels.CAPACITY_TYPE_SPOT, apilabels.CAPACITY_TYPE_ON_DEMAND)
        )
        out.append(
            InstanceType(
                name=name,
                requirements=Requirements(
                    [
                        Requirement.new(apilabels.LABEL_INSTANCE_TYPE, "In", [name]),
                        Requirement.new(
                            apilabels.LABEL_ARCH, "In", [apilabels.ARCHITECTURE_AMD64]
                        ),
                        Requirement.new(apilabels.LABEL_OS, "In", ["linux"]),
                        Requirement.new(apilabels.LABEL_TOPOLOGY_ZONE, "In", zones),
                        Requirement.new(
                            apilabels.CAPACITY_TYPE_LABEL_KEY,
                            "In",
                            [
                                apilabels.CAPACITY_TYPE_SPOT,
                                apilabels.CAPACITY_TYPE_ON_DEMAND,
                            ],
                        ),
                    ]
                ),
                offerings=offerings,
                capacity={
                    RESOURCE_CPU: float(cpu),
                    RESOURCE_MEMORY: mem,
                    RESOURCE_PODS: 110.0,
                },
            )
        )
    return out


class FakeCloudProvider(CloudProvider):
    def __init__(
        self,
        instance_types: Optional[List[InstanceType]] = None,
        unavailable_offerings=None,
        clock=None,
    ):
        from karpenter_core_tpu.cloudprovider.unavailableofferings import (
            UnavailableOfferings,
        )

        self.instance_types = instance_types or fake_instance_types(5)
        self.instance_types_for_nodepool: dict = {}
        self.create_calls: list = []
        self.delete_calls: list = []
        self.next_create_error: Optional[Exception] = None
        self.allowed_create_calls: Optional[int] = None
        self.drifted: str = ""
        self._created: dict = {}
        self._counter = itertools.count(1)
        # same stockout/ICE-cache seam as the kwok provider (see kwok.py);
        # `is None` because an empty shared cache is falsy but still shared.
        # Pass the test's fake clock (this provider has no kube to derive
        # one from) or ICE TTLs expire on WALL time under a stepped clock.
        self.stockouts: set = set()
        self.unavailable_offerings = (
            unavailable_offerings
            if unavailable_offerings is not None
            else UnavailableOfferings(clock)
        )

    def get_instance_types(self, nodepool) -> List[InstanceType]:
        name = getattr(nodepool, "name", nodepool)
        return list(self.instance_types_for_nodepool.get(name, self.instance_types))

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        if self.next_create_error is not None:
            err, self.next_create_error = self.next_create_error, None
            raise err
        if (
            self.allowed_create_calls is not None
            and len(self.create_calls) >= self.allowed_create_calls
        ):
            raise RuntimeError("create call limit exceeded")
        self.create_calls.append(node_claim)
        reqs = Requirements.from_node_selector_requirements_with_min_values(
            node_claim.spec.requirements
        )
        it = next(
            (
                t
                for t in self.get_instance_types(node_claim.nodepool_name)
                if not reqs.intersects(t.requirements)
            ),
            None,
        )
        if it is None:
            raise RuntimeError("no compatible instance type")
        candidates = it.offerings.available().compatible(reqs)
        offering = min(
            (
                o
                for o in candidates
                if not self.unavailable_offerings.is_unavailable(o.key(it.name))
            ),
            key=lambda o: o.price,
            default=None,
        )
        if offering is None and candidates:
            # every compatible offering is ICE-cached: the launch must fail
            # like kwok's (no context — they are already cached), not
            # silently succeed with empty zone/capacity-type labels
            raise InsufficientCapacityError(
                f"no available offering for {it.name}"
            )
        if offering is not None and offering.key(it.name) in self.stockouts:
            raise InsufficientCapacityError(
                f"insufficient capacity for {it.name}",
                offerings=[offering.key(it.name)],
            )
        node_claim.status.provider_id = f"fake://{next(self._counter)}"
        node_claim.status.capacity = dict(it.capacity)
        node_claim.status.allocatable = dict(it.allocatable())
        node_claim.metadata.labels.update(
            {
                apilabels.LABEL_INSTANCE_TYPE: it.name,
                apilabels.LABEL_TOPOLOGY_ZONE: offering.zone if offering else "",
                apilabels.CAPACITY_TYPE_LABEL_KEY: offering.capacity_type
                if offering
                else "",
            }
        )
        node_claim.conditions.set_true(COND_LAUNCHED, "Launched")
        self._created[node_claim.status.provider_id] = node_claim
        return node_claim

    def delete(self, node_claim: NodeClaim) -> None:
        self.delete_calls.append(node_claim)
        if node_claim.status.provider_id not in self._created:
            raise NodeClaimNotFoundError(node_claim.status.provider_id)
        del self._created[node_claim.status.provider_id]

    def get(self, provider_id: str) -> NodeClaim:
        if provider_id not in self._created:
            raise NodeClaimNotFoundError(provider_id)
        return self._created[provider_id]

    def list(self) -> List[NodeClaim]:
        return list(self._created.values())

    def is_drifted(self, node_claim: NodeClaim) -> str:
        return self.drifted
