"""UnavailableOfferings: the control plane's ICE cache.

The reference's AWS provider keeps a TTL'd cache of offerings that returned
InsufficientCapacityError so the next launch (and the next solve) skips
them (pkg/cache/unavailableofferings.go; the 3-minute TTL is its
UnavailableOfferingsTTL). Here the cache is a core object shared by three
consumers:

* the NodeClaim lifecycle controller MARKS offerings from the typed
  ``InsufficientCapacityError.offerings`` context when a launch fails;
* both solve paths CONSUME the snapshot — the greedy scheduler filters
  offering availability, the device solver masks its offerings tensor
  (and the solverd sidecar receives the same set over the wire);
* the cloud provider's create path SKIPS cached offerings when picking,
  so a claim whose requirement lattice still admits a stocked-out offering
  cannot re-pick it inside the TTL (the create→ICE→delete livelock).

Entries expire on read against the injected clock, so fake-clock tests can
elapse the TTL deterministically and watch the offering return to service.
"""
from __future__ import annotations

from typing import Dict, Optional

from karpenter_core_tpu.cloudprovider.types import OfferingKey

# the reference AWS provider's UnavailableOfferingsTTL (3 minutes): long
# enough to ride out a stockout, short enough that capacity returning to a
# zone is picked back up without an operator restart
UNAVAILABLE_OFFERINGS_TTL = 180.0


class UnavailableOfferings:
    def __init__(self, clock=None, ttl: float = UNAVAILABLE_OFFERINGS_TTL):
        from karpenter_core_tpu.utils.clock import Clock

        self.clock = clock or Clock()
        self.ttl = ttl
        self._expiry: Dict[OfferingKey, float] = {}

    # -- writes ------------------------------------------------------------

    def mark(self, key, ttl: Optional[float] = None) -> None:
        """Record one stocked-out offering; re-marking refreshes the TTL."""
        key = OfferingKey(*key)
        self._expiry[key] = self.clock.now() + (ttl if ttl is not None else self.ttl)
        self._export()

    # -- reads -------------------------------------------------------------

    def is_unavailable(self, key) -> bool:
        self._expire()
        return OfferingKey(*key) in self._expiry

    def snapshot(self) -> "frozenset[OfferingKey]":
        """The live (unexpired) unavailable set — what a solve consumes."""
        self._expire()
        return frozenset(self._expiry)

    def __len__(self) -> int:
        self._expire()
        return len(self._expiry)

    # -- internals ---------------------------------------------------------

    def _expire(self) -> None:
        now = self.clock.now()
        stale = [k for k, t in self._expiry.items() if t <= now]
        if stale:
            for k in stale:
                del self._expiry[k]
            self._export()

    def _export(self) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        m.UNAVAILABLE_OFFERINGS_COUNT.set(float(len(self._expiry)))
