from karpenter_core_tpu.cloudprovider.types import (  # noqa: F401
    CloudProvider,
    InstanceType,
    Offering,
    Offerings,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
)
