"""Cloud-provider abstraction (reference: pkg/cloudprovider/types.go:56-399).

InstanceType is the unit the solver tensorizes: its Requirements become mask
rows over the solve vocabulary, Capacity/Overhead become the allocatable
matrix, and the Offering lattice becomes the price/availability tensors.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, NamedTuple, Optional

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodeclaim import NodeClaim
from karpenter_core_tpu.api.objects import ResourceList
from karpenter_core_tpu.scheduling import Requirements
from karpenter_core_tpu.utils import resources as resutil


class OfferingKey(NamedTuple):
    """The identity of one purchase option: the instance-type × zone ×
    capacity-type triple a capacity stockout names. A plain tuple subclass,
    so wire-decoded ``(it, zone, ct)`` tuples compare equal."""

    instance_type: str
    zone: str
    capacity_type: str


@dataclass
class Offering:
    """A (zone, capacity-type) purchase option (types.go:244-252)."""

    requirements: Requirements
    price: float
    available: bool = True

    def key(self, instance_type: str) -> OfferingKey:
        return OfferingKey(instance_type, self.zone, self.capacity_type)

    @property
    def zone(self) -> str:
        req = self.requirements.get(apilabels.LABEL_TOPOLOGY_ZONE)
        values = req.sorted_values()
        return values[0] if values else ""

    @property
    def capacity_type(self) -> str:
        req = self.requirements.get(apilabels.CAPACITY_TYPE_LABEL_KEY)
        values = req.sorted_values()
        return values[0] if values else ""


class Offerings(list):
    """list[Offering] with the reference's filter/selector helpers
    (types.go:256-310)."""

    def available(self) -> "Offerings":
        return Offerings(o for o in self if o.available)

    def compatible(self, reqs: Requirements) -> "Offerings":
        return Offerings(
            o for o in self if not reqs.intersects(o.requirements)
        )

    def has_compatible(self, reqs: Requirements) -> bool:
        return any(not reqs.intersects(o.requirements) for o in self)

    def cheapest(self) -> Optional[Offering]:
        return min(self, key=lambda o: o.price, default=None)

    def most_expensive(self) -> Optional[Offering]:
        return max(self, key=lambda o: o.price, default=None)

    def worst_launch_price(self, reqs: Requirements) -> float:
        """Most expensive offering that could be launched under reqs — the
        price bound used by consolidation (types.go:294-310)."""
        compatible = self.compatible(reqs)
        o = compatible.most_expensive()
        return o.price if o else 0.0


@dataclass
class InstanceType:
    """types.go:86-115. allocatable = capacity - overhead, cached."""

    name: str
    requirements: Requirements
    offerings: Offerings
    capacity: ResourceList
    overhead: ResourceList = field(default_factory=dict)
    _allocatable: Optional[ResourceList] = field(default=None, repr=False)

    def allocatable(self) -> ResourceList:
        if self._allocatable is None:
            self._allocatable = resutil.subtract(self.capacity, self.overhead)
        return self._allocatable


def order_by_price(
    instance_types: Iterable[InstanceType], reqs: Requirements
) -> List[InstanceType]:
    """Sort by the cheapest compatible+available offering price
    (types.go:117-134)."""

    def price(it: InstanceType) -> float:
        o = it.offerings.available().compatible(reqs).cheapest()
        return o.price if o else float("inf")

    return sorted(instance_types, key=price)


def satisfies_min_values(
    instance_types: Iterable[InstanceType], reqs: Requirements
) -> "tuple[int, Optional[str]]":
    """Check every MinValues requirement is satisfiable across the instance
    types jointly; returns (max needed count, error) (types.go:178-212)."""
    needed = 0
    err = None
    for key, req in reqs.items():
        if req.min_values is None:
            continue
        distinct = set()
        for it in instance_types:
            it_req = it.requirements.get(key)
            if it_req.operator() == "In":
                distinct.update(
                    v for v in it_req.sorted_values() if req.has(v)
                )
        if len(distinct) < req.min_values:
            err = (
                f"minValues requirement is not met for label {key} "
                f"(found {len(distinct)}, need {req.min_values})"
            )
        needed = max(needed, req.min_values)
    return needed, err


def truncate_instance_types(
    instance_types: List[InstanceType], reqs: Requirements, max_items: int
) -> "tuple[List[InstanceType], Optional[str]]":
    """Truncate a price-ordered list while preserving minValues feasibility
    (types.go:216-240)."""
    truncated = instance_types[:max_items]
    if Requirements(reqs.values()).has_min_values():
        _, err = satisfies_min_values(truncated, reqs)
        if err:
            return instance_types, err
    return truncated, None


def apply_unavailable(
    instance_types: Dict[str, List[InstanceType]],
    unavailable: "frozenset[OfferingKey] | set",
) -> Dict[str, List[InstanceType]]:
    """Project an unavailable-offerings set onto per-pool catalogs: instance
    types with a hit get a shallow copy whose stocked-out offerings are
    marked ``available=False``; untouched types keep their identity, and
    objects shared across pools stay shared (the catalog-union dedupe and
    the wire codec's identity table both key on ``id``)."""
    if not unavailable:
        return instance_types
    memo: Dict[int, InstanceType] = {}

    def one(it: InstanceType) -> InstanceType:
        got = memo.get(id(it))
        if got is None:
            hit = any(
                o.available and o.key(it.name) in unavailable
                for o in it.offerings
            )
            if hit:
                got = InstanceType(
                    name=it.name,
                    requirements=it.requirements,
                    offerings=Offerings(
                        Offering(
                            requirements=o.requirements,
                            price=o.price,
                            available=o.available
                            and o.key(it.name) not in unavailable,
                        )
                        for o in it.offerings
                    ),
                    capacity=it.capacity,
                    overhead=it.overhead,
                )
            else:
                got = it
            memo[id(it)] = got
        return got

    return {pool: [one(it) for it in its] for pool, its in instance_types.items()}


# -- typed errors (types.go:312-399) ----------------------------------------

class CloudProviderError(Exception):
    pass


class NodeClaimNotFoundError(CloudProviderError):
    pass


class InsufficientCapacityError(CloudProviderError):
    """A launch failed because capacity was stocked out. ``offerings``
    carries the OfferingKeys the provider observed unavailable so the
    control plane can mark them in its UnavailableOfferings cache (the
    reference's AWS provider attaches the same context to its ICE cache,
    pkg/cache/unavailableofferings.go) instead of re-solving onto the
    identical stocked-out offering and livelocking."""

    def __init__(self, message: str, offerings: Iterable[OfferingKey] = ()):
        super().__init__(message)
        self.offerings = tuple(offerings)


class NodeClassNotReadyError(CloudProviderError):
    pass


class CreateError(CloudProviderError):
    def __init__(self, message: str, condition_reason: str = "", condition_message: str = ""):
        super().__init__(message)
        self.condition_reason = condition_reason
        self.condition_message = condition_message


@dataclass
class RepairPolicy:
    condition_type: str
    condition_status: str
    toleration_duration: float  # seconds


class CloudProvider(abc.ABC):
    """The provider interface (types.go:56-82)."""

    @abc.abstractmethod
    def create(self, node_claim: NodeClaim) -> NodeClaim:
        """Launch capacity; returns hydrated claim with provider_id, capacity,
        resolved instance-type labels."""

    @abc.abstractmethod
    def delete(self, node_claim: NodeClaim) -> None:
        ...

    @abc.abstractmethod
    def get(self, provider_id: str) -> NodeClaim:
        ...

    @abc.abstractmethod
    def list(self) -> List[NodeClaim]:
        ...

    @abc.abstractmethod
    def get_instance_types(self, nodepool) -> List[InstanceType]:
        ...

    @abc.abstractmethod
    def is_drifted(self, node_claim: NodeClaim) -> str:
        """Returns a drift reason or ''."""

    def repair_policies(self) -> List[RepairPolicy]:
        return []

    @property
    def name(self) -> str:
        return type(self).__name__.lower()
