"""CloudProvider metrics decorator (reference: pkg/cloudprovider/metrics/
cloudprovider.go): wraps any provider with per-method duration histograms
and error counters, transparently forwarding everything else.
"""
from __future__ import annotations

from karpenter_core_tpu.metrics.registry import REGISTRY

METHOD_DURATION = REGISTRY.histogram(
    "cloudprovider_duration_seconds",
    "Duration of cloud provider method calls",
)
METHOD_ERRORS = REGISTRY.counter(
    "cloudprovider_errors_total",
    "Cloud provider method errors, by method and error type",
)

_WRAPPED = (
    "create",
    "delete",
    "get",
    "list",
    "get_instance_types",
    "is_drifted",
    "repair_policies",
)


class MetricsDecorator:
    """decorator.Decorate(cloudProvider) — same interface, instrumented."""

    def __init__(self, provider):
        self._provider = provider

    def __getattr__(self, name: str):
        attr = getattr(self._provider, name)
        if name not in _WRAPPED or not callable(attr):
            return attr

        def wrapped(*args, **kwargs):
            labels = {
                "method": name,
                "provider": type(self._provider).__name__,
            }
            with METHOD_DURATION.time(labels):
                try:
                    return attr(*args, **kwargs)
                except Exception as e:
                    METHOD_ERRORS.inc(
                        {**labels, "error": type(e).__name__}
                    )
                    raise

        return wrapped
