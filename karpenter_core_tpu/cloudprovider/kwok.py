"""KWOK-style fake cloud provider — the scale-bench harness.

Mirrors the reference's in-tree kwok provider: Create materializes a fake
Node object directly in the (in-memory) apiserver with the unregistered
taint, picking the cheapest compatible offering (reference:
kwok/cloudprovider/cloudprovider.go:53-64,143-191); the instance catalog is
generated as families {c,s,m} × cpu grid × os × arch with 4 zones ×
{spot, on-demand} offerings and price linear in cpu+mem, spot = 0.7×OD
(reference: kwok/tools/gen_instance_types.go:36-115).
"""
from __future__ import annotations

import itertools
from typing import List, Optional

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodeclaim import (
    COND_LAUNCHED,
    NodeClaim,
)
from karpenter_core_tpu.api.objects import (
    Node,
    NodeStatus,
    ObjectMeta,
    RESOURCE_CPU,
    RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    Taint,
)
from karpenter_core_tpu.cloudprovider.types import (
    CloudProvider,
    InsufficientCapacityError,
    InstanceType,
    NodeClaimNotFoundError,
    Offering,
    Offerings,
)
from karpenter_core_tpu.scheduling import Requirement, Requirements
from karpenter_core_tpu.scheduling.taints import UNREGISTERED_NO_EXECUTE_TAINT

KWOK_ZONES = ["zone-a", "zone-b", "zone-c", "zone-d"]
DEFAULT_CPU_GRID = [1, 2, 4, 8, 16, 32, 48, 64, 96, 128, 192, 256]
MEM_FACTORS = {2: "c", 4: "s", 8: "m"}  # GiB per cpu -> family

GIB = 2.0**30


def build_catalog(
    cpu_grid: Optional[List[int]] = None,
    mem_factors: Optional[List[int]] = None,
    oses: Optional[List[str]] = None,
    arches: Optional[List[str]] = None,
    zones: Optional[List[str]] = None,
) -> List[InstanceType]:
    """Generate the synthetic instance catalog. Defaults give the reference's
    144 types (12 cpu × 3 families × 2 os × 2 arch); widen the grids to reach
    the ~800-type bench catalog (BASELINE.md)."""
    cpu_grid = cpu_grid or DEFAULT_CPU_GRID
    mem_factors = mem_factors or list(MEM_FACTORS)
    oses = oses or ["linux", "windows"]
    arches = arches or [apilabels.ARCHITECTURE_AMD64, apilabels.ARCHITECTURE_ARM64]
    zones = zones or KWOK_ZONES

    out = []
    for cpu, mem_factor, os_name, arch in itertools.product(
        cpu_grid, mem_factors, oses, arches
    ):
        family = MEM_FACTORS.get(mem_factor, "e")
        name = f"{family}-{cpu}x-{arch}-{os_name}"
        mem_gib = cpu * mem_factor
        pods = min(cpu * 16, 1024)
        capacity = {
            RESOURCE_CPU: float(cpu),
            RESOURCE_MEMORY: mem_gib * GIB,
            RESOURCE_PODS: float(pods),
            RESOURCE_EPHEMERAL_STORAGE: 20 * GIB,
        }
        price = 0.025 * cpu + 0.001 * (mem_gib * GIB) / 1e9
        offerings = Offerings()
        for zone in zones:
            for ct in (apilabels.CAPACITY_TYPE_SPOT, apilabels.CAPACITY_TYPE_ON_DEMAND):
                offerings.append(
                    Offering(
                        requirements=Requirements(
                            [
                                Requirement.new(
                                    apilabels.CAPACITY_TYPE_LABEL_KEY, "In", [ct]
                                ),
                                Requirement.new(
                                    apilabels.LABEL_TOPOLOGY_ZONE, "In", [zone]
                                ),
                            ]
                        ),
                        price=price * 0.7 if ct == apilabels.CAPACITY_TYPE_SPOT else price,
                        available=True,
                    )
                )
        requirements = Requirements(
            [
                Requirement.new(apilabels.LABEL_INSTANCE_TYPE, "In", [name]),
                Requirement.new(apilabels.LABEL_ARCH, "In", [arch]),
                Requirement.new(apilabels.LABEL_OS, "In", [os_name]),
                Requirement.new(
                    apilabels.LABEL_TOPOLOGY_ZONE, "In", list(zones)
                ),
                Requirement.new(
                    apilabels.CAPACITY_TYPE_LABEL_KEY,
                    "In",
                    [apilabels.CAPACITY_TYPE_SPOT, apilabels.CAPACITY_TYPE_ON_DEMAND],
                ),
                Requirement.new("karpenter.kwok.sh/instance-size", "In", [f"{cpu}x"]),
                Requirement.new("karpenter.kwok.sh/instance-family", "In", [family]),
                Requirement.new(
                    "karpenter.kwok.sh/instance-cpu", "In", [str(cpu)]
                ),
                Requirement.new(
                    "karpenter.kwok.sh/instance-memory", "In", [str(mem_gib)]
                ),
            ]
        )
        out.append(
            InstanceType(
                name=name,
                requirements=requirements,
                offerings=offerings,
                capacity=capacity,
                overhead={RESOURCE_CPU: 0.1, RESOURCE_MEMORY: 0.2 * GIB},
            )
        )
    return out


def bench_catalog(n_target: int = 800) -> List[InstanceType]:
    """A widened catalog of ~n_target types for the 50k-pod benchmark
    (BASELINE.md: 'extensible to ~800')."""
    cpu_grid = sorted(set(list(range(1, 49)) + DEFAULT_CPU_GRID))
    mem_factors = [2, 4, 8, 16]
    catalog = build_catalog(cpu_grid=cpu_grid, mem_factors=mem_factors)
    return catalog[:n_target]


class KwokCloudProvider(CloudProvider):
    """Fake provider backed by the in-memory kube store."""

    def __init__(
        self,
        kube,
        instance_types: Optional[List[InstanceType]] = None,
        unavailable_offerings=None,
        rack_size: int = 0,
    ):
        from karpenter_core_tpu.cloudprovider.unavailableofferings import (
            UnavailableOfferings,
        )
        from karpenter_core_tpu.utils.clock import Clock

        self.kube = kube
        # KubeClient implementations other than the in-memory store carry no
        # clock; condition stamping falls back to wall time
        self.clock = getattr(kube, "clock", None) or Clock()
        self.instance_types = instance_types or build_catalog()
        self._by_name = {it.name: it for it in self.instance_types}
        self._counter = itertools.count(1)
        # rack topology stamping (topoaware, ISSUE 20), OFF by default so
        # existing catalogs stay rack-less and the topo layer disengaged:
        # rack_size >= 1 assigns each created node a deterministic rack
        # (racks of rack_size nodes per zone, filled in creation order)
        # and superpod (two racks per superpod) label — the synthetic
        # stand-in for a real provider's physical-placement attribution
        self.rack_size = rack_size
        self._zone_seq: dict = {}
        self.allow_insufficient_capacity = False
        # ground-truth capacity stockouts: OfferingKeys create cannot fill.
        # Tests / the chaos harness's ICE storms write this set; create
        # raises a typed ICE (with the offering context) when its pick is in
        # it — the seam the UnavailableOfferings cache learns from.
        self.stockouts: set = set()
        # shared ICE cache (the AWS provider consults the same cache in its
        # own CreateFleet path): create skips offerings already known
        # unavailable so a claim whose requirements still admit them cannot
        # livelock through the identical stockout inside the TTL.
        # `is None`, not truthiness — an EMPTY cache passed by the operator
        # is falsy (len 0) but must be adopted, or lifecycle marks a
        # different cache than this create path consults
        self.unavailable_offerings = (
            unavailable_offerings
            if unavailable_offerings is not None
            else UnavailableOfferings(self.clock)
        )

    def get_instance_types(self, nodepool) -> List[InstanceType]:
        return list(self.instance_types)

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        reqs = Requirements.from_node_selector_requirements_with_min_values(
            node_claim.spec.requirements
        )
        # pick cheapest compatible instance type + offering
        # (kwok cloudprovider.go:143-191), skipping offerings the shared ICE
        # cache already knows are stocked out — the fleet-request analogue of
        # the AWS provider excluding cached-unavailable pools
        best = None
        for it in self.instance_types:
            if reqs.intersects(it.requirements):
                continue
            for offering in it.offerings.available().compatible(reqs):
                if self.unavailable_offerings.is_unavailable(
                    offering.key(it.name)
                ):
                    continue
                if best is None or offering.price < best[1].price:
                    best = (it, offering)
        if best is None:
            raise InsufficientCapacityError(
                f"no compatible instance type for {node_claim.name}"
            )
        it, offering = best
        key = offering.key(it.name)
        if key in self.stockouts:
            # actual capacity is out: fail the launch NAMING the offering,
            # so lifecycle can mark it unavailable and the re-solve lands on
            # the next-cheapest available one instead of repeating this pick
            raise InsufficientCapacityError(
                f"insufficient capacity for {key.instance_type} in "
                f"{key.zone} ({key.capacity_type})",
                offerings=[key],
            )
        seq = next(self._counter)
        provider_id = f"kwok://{node_claim.name}-{seq}"
        node_claim.status.provider_id = provider_id
        node_claim.status.capacity = dict(it.capacity)
        node_claim.status.allocatable = dict(it.allocatable())
        node_claim.status.image_id = "kwok-ami"
        labels = dict(node_claim.metadata.labels)
        # derived single-value requirement labels — including well-known keys
        # like region that only the provider may inject (reference kwok
        # addInstanceLabels, cloudprovider.go:200-205)
        for req in node_claim.spec.requirements:
            if req.operator == "In" and len(req.values) == 1:
                labels[req.key] = req.values[0]
        labels.update(
            {
                apilabels.LABEL_INSTANCE_TYPE: it.name,
                apilabels.LABEL_ARCH: it.requirements.get(apilabels.LABEL_ARCH).any_value(),
                apilabels.LABEL_OS: it.requirements.get(apilabels.LABEL_OS).any_value(),
                apilabels.LABEL_TOPOLOGY_ZONE: offering.zone,
                apilabels.CAPACITY_TYPE_LABEL_KEY: offering.capacity_type,
            }
        )
        if self.rack_size > 0:
            n = self._zone_seq.get(offering.zone, 0)
            self._zone_seq[offering.zone] = n + 1
            rack = n // self.rack_size
            labels[apilabels.LABEL_TOPOLOGY_RACK] = (
                f"{offering.zone}-r{rack}"
            )
            labels[apilabels.LABEL_TOPOLOGY_SUPERPOD] = (
                f"{offering.zone}-s{rack // 2}"
            )
        node_claim.metadata.labels = labels
        node_claim.conditions.set_true(
            COND_LAUNCHED, "Launched", now=self.clock.now()
        )

        # Materialize the fake Node with the unregistered taint; the
        # registration controller adopts it (kwok cloudprovider.go:53-64).
        node = Node(
            metadata=ObjectMeta(
                name=node_claim.name,
                labels=dict(labels),
            ),
            provider_id=provider_id,
            taints=[UNREGISTERED_NO_EXECUTE_TAINT],
            status=NodeStatus(
                capacity=dict(it.capacity),
                allocatable=dict(it.allocatable()),
                conditions=[("Ready", "True")],
            ),
        )
        self.kube.create(node)
        return node_claim

    def delete(self, node_claim: NodeClaim) -> None:
        node = self.kube.get_node_by_provider_id(node_claim.status.provider_id)
        if node is None:
            raise NodeClaimNotFoundError(node_claim.status.provider_id)
        self.kube.delete(node)

    def get(self, provider_id: str) -> NodeClaim:
        node = self.kube.get_node_by_provider_id(provider_id)
        if node is None:
            raise NodeClaimNotFoundError(provider_id)
        nc = NodeClaim()
        nc.metadata.name = node.name
        nc.metadata.labels = dict(node.labels)
        nc.status.provider_id = provider_id
        nc.status.capacity = dict(node.status.capacity)
        return nc

    def list(self) -> List[NodeClaim]:
        return [
            self.get(n.provider_id)
            for n in self.kube.list_nodes()
            if n.provider_id.startswith("kwok://")
        ]

    def is_drifted(self, node_claim: NodeClaim) -> str:
        return ""

    @property
    def name(self) -> str:
        return "kwok"
