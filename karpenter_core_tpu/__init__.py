"""karpenter_core_tpu — a TPU-native cluster-autoscaling framework.

A from-scratch rebuild of the capabilities of ``sigs.k8s.io/karpenter``
(reference: /root/reference) in which the two hot combinatorial loops —
the provisioning scheduler's first-fit-decreasing bin-pack
(``pkg/controllers/provisioning/scheduling/scheduler.go:208``) and the
consolidation candidate sweep
(``pkg/controllers/disruption/multinodeconsolidation.go:110``) — are
reformulated as batched pod-class × InstanceType tensor assignment in JAX,
executed on TPU, while the surrounding control plane (cluster state,
controllers, cloud-provider abstraction, lifecycle) is a synchronous,
deterministic Python rebuild of the reference's Go reconcilers (the
determinism is load-bearing: it is what makes the device solver's
resharding bit-exactness testable).

Layout (mirrors SURVEY.md §7):
  api/            CRD-equivalent object model (NodePool, NodeClaim, Pod, Node)
  scheduling/     L1 requirements/taints algebra (host side)
  utils/          resource arithmetic, pod predicates, pdb, disruption cost
  ops/            pure jittable JAX ops: compat matmuls, fit masks, FFD scan
  models/         full solver programs (provisioning solve, consolidation sweep)
  solver/         host<->device boundary: vocab interning, snapshot codec, Solver API
  parallel/       device mesh + sharding of the solve across ICI
  state/          cluster state cache
  cloudprovider/  provider interface + kwok bench provider + test fake
  kube/           in-memory apiserver-equivalent object store with watches
  controllers/    provisioning / disruption / lifecycle / termination reconcilers
  operator/       options, operator runtime
"""

__version__ = "0.1.0"
