"""The operator's served HTTP surface: /healthz, /readyz, /metrics.

The reference's manager serves liveness/readiness probes and the
Prometheus endpoint from the operator process (operator.go:181-198 healthz
/readyz wiring, metrics server port at :105-135); this is the same surface
over the in-process registry — probes delegate to Operator.healthz/readyz
(the cluster-Synced gate) and /metrics renders the exposition format from
metrics.registry.REGISTRY.
"""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _Handler(BaseHTTPRequestHandler):
    operator = None  # bound per server

    def log_message(self, *args) -> None:
        pass

    def _send(self, code: int, body: str, ctype: str = "text/plain") -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:
        path = self.path.split("?")[0]
        if path == "/healthz":
            ok = self.operator.healthz()
            self._send(200 if ok else 503, "ok" if ok else "unhealthy")
        elif path == "/readyz":
            ok = self.operator.readyz()
            self._send(200 if ok else 503, "ready" if ok else "not ready")
        elif path == "/metrics":
            from karpenter_core_tpu.metrics.registry import REGISTRY

            self._send(
                200, REGISTRY.render(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        else:
            self._send(404, "not found")


def start_health_server(
    operator, port: int = 8081, host: str = "0.0.0.0"
) -> ThreadingHTTPServer:
    """Serve probes+metrics on host:port in a daemon thread; returns the
    server (port 0 picks a free one — server_address[1]). Binds all
    interfaces by default — kubelet httpGet probes hit the pod IP, not
    loopback (the reference's metrics/probe listeners do the same)."""
    handler = type("BoundHealth", (_Handler,), {"operator": operator})
    httpd = ThreadingHTTPServer((host, port), handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd
