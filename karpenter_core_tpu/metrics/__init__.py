from karpenter_core_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    REGISTRY,
)

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "REGISTRY"]
