"""Named metric instruments on the shared registry.

One module owns every metric name so emission sites stay one-liners and
the judge/ops surface is greppable. Names mirror the reference's
(scheduling/metrics.go:34-90, disruption/metrics.go:43-85,
state/metrics.go:36-67, pkg/controllers/metrics/{pod,node,nodepool}) plus
the TPU-first solver instruments the reference has no counterpart for.
"""
from __future__ import annotations

from karpenter_core_tpu.metrics.registry import REGISTRY

# -- scheduler (scheduling/metrics.go:34-90) -------------------------------

SCHEDULING_DURATION = REGISTRY.histogram(
    "provisioner_scheduling_duration_seconds",
    "Duration of one scheduling solve",
)
QUEUE_DEPTH = REGISTRY.gauge(
    "provisioner_scheduling_queue_depth",
    "Pods entering the most recent scheduling solve",
)
UNSCHEDULABLE_PODS = REGISTRY.gauge(
    "provisioner_scheduling_unschedulable_pods_count",
    "Pods the most recent solve could not place",
)
IGNORED_PODS = REGISTRY.gauge(
    "provisioner_scheduling_ignored_pod_count",
    "Pods excluded from the solve (failed volume validation etc.)",
)

# -- disruption (disruption/metrics.go:43-85) ------------------------------

DISRUPTION_DECISIONS = REGISTRY.counter(
    "voluntary_disruption_decisions_total",
    "Disruption commands executed, by decision and reason",
)
DISRUPTION_ELIGIBLE_NODES = REGISTRY.gauge(
    "voluntary_disruption_eligible_nodes",
    "Nodes eligible for disruption, by reason",
)
DISRUPTION_VALIDATION_FAILURES = REGISTRY.counter(
    "voluntary_disruption_validation_failures_total",
    "Commands invalidated during the validation TTL",
)
CONSOLIDATION_TIMEOUTS = REGISTRY.counter(
    "consolidation_timeouts_total",
    "Consolidation sweeps abandoned at their per-poll time budget, by type"
    " (metrics.go ConsolidationTimeoutsTotal)",
)

NODES_POD_REQUESTS = REGISTRY.gauge(
    "nodes_total_pod_requests",
    "Bound pods' aggregate requests, by resource"
    " (metrics/node/controller.go exporter)",
)
NODES_POD_LIMITS = REGISTRY.gauge(
    "nodes_total_pod_limits",
    "Bound pods' aggregate limits, by resource"
    " (metrics/node/controller.go exporter; statenode.go:429 LimitsForPods)",
)

# -- status conditions (operatorpkg status controllers, controllers.go:103-105)

STATUS_CONDITION_TRANSITIONS = REGISTRY.counter(
    "operator_status_condition_transitions_total",
    "Condition flips on NodeClaims/NodePools, by kind/type/status",
)
STATUS_CONDITION_COUNT = REGISTRY.gauge(
    "operator_status_condition_count",
    "Current conditions by kind/type/status",
)

# -- cluster state (state/metrics.go:36-67) --------------------------------

CLUSTER_NODE_COUNT = REGISTRY.gauge(
    "cluster_state_node_count", "Nodes tracked in cluster state"
)
CLUSTER_SYNCED = REGISTRY.gauge(
    "cluster_state_synced", "1 when cluster state matches the store"
)

# -- exporters (pkg/controllers/metrics/{pod,node,nodepool}) ---------------

PODS_STATE = REGISTRY.gauge("pods_state", "Pod count by phase")
NODES_ALLOCATABLE = REGISTRY.gauge(
    "nodes_allocatable", "Summed node allocatable by resource"
)
NODEPOOL_USAGE = REGISTRY.gauge(
    "nodepool_usage", "In-use capacity per nodepool and resource"
)
NODEPOOL_LIMIT = REGISTRY.gauge(
    "nodepool_limit", "Configured limit per nodepool and resource"
)

# -- reconcile fault isolation (controller-runtime's controller_runtime_
# reconcile_errors_total + the health probe's crash-loop gate) -------------

RECONCILE_ERRORS = REGISTRY.counter(
    "controller_reconcile_errors_total",
    "Reconciler invocations that raised, by controller and error type; the"
    " pass survives (the exception is isolated to the controller's backoff)",
)
CONTROLLER_CRASHLOOPING = REGISTRY.gauge(
    "controller_crashlooping",
    "Controllers at/past the consecutive-error-pass threshold that flips"
    " readyz",
)

# -- ICE / unavailable offerings (AWS provider's ICE cache, surfaced core) --

UNAVAILABLE_OFFERINGS_COUNT = REGISTRY.gauge(
    "cloudprovider_unavailable_offerings",
    "Offerings currently marked unavailable (instance-type×zone×capacity-"
    "type) in the TTL'd ICE cache both solve paths consume",
)
INSUFFICIENT_CAPACITY_ERRORS = REGISTRY.counter(
    "nodeclaims_insufficient_capacity_total",
    "NodeClaim launches abandoned on InsufficientCapacityError, by"
    " capacity_type/zone of the stocked-out offering ('' when the provider"
    " attached no offering context)",
)

# -- TPU solver (no reference counterpart; Weak #6 of VERDICT r3) ----------

SOLVER_SOLVE_DURATION = REGISTRY.histogram(
    "solver_device_solve_duration_seconds",
    "End-to-end device solve (prepare + kernel + decode), per round",
)
SOLVER_PREPARE_DURATION = REGISTRY.histogram(
    "solver_prepare_duration_seconds",
    "Host-side snapshot encode / tensor build per round",
)
SOLVER_KERNEL_DURATION = REGISTRY.histogram(
    "solver_kernel_duration_seconds",
    "Device FFD scan including the device->host transfer, per round",
)
SOLVER_DECODE_DURATION = REGISTRY.histogram(
    "solver_decode_duration_seconds",
    "Host decode of device placements, per round",
)
SOLVER_HOST_FALLBACK_PODS = REGISTRY.counter(
    "solver_host_fallback_pods_total",
    "Pods that left the device path, by cause "
    "(ineligible|deferred|divergent) — the silent-divergence signal",
)
SOLVER_LIMIT_DROPPED_CLAIMS = REGISTRY.counter(
    "solver_limit_dropped_claims_total",
    "Solved claims dropped at provision() by NodePool limits — near-limit"
    " solve/drop/re-solve churn the greedy in-solve check never hits",
)
SOLVER_RELAX_ROUNDS = REGISTRY.counter(
    "solver_relaxation_rounds_total",
    "Preference-relaxation re-solves",
)
SOLVER_RELAX_BACKEND = REGISTRY.counter(
    "solver_relax_backend_total",
    "relaxsolve backend outcomes per solve (won|lost|noop|cached|deadline"
    "|overflow|infeasible) — won/lost judge the convex-relaxation"
    " candidate against the FFD anytime answer; deadline means the"
    " budget expired and the FFD answer served",
)
SOLVER_PREP_CACHE = REGISTRY.counter(
    "solver_prepared_cache_total",
    "Prepared-state (class batch) cache lookups by outcome (hit|miss) —"
    " the incremental re-solve signal: steady-state solves should hit",
)
SOLVER_FETCH_BYTES = REGISTRY.counter(
    "solver_device_fetch_bytes_total",
    "Bytes fetched device->host per solve round (per-class decision planes"
    " + used-slot topology windows, after slicing)",
)

# -- solverd sidecar RPC (solver/{service,remote,supervisor}.py) -----------

SOLVER_RPC_PHASE_DURATION = REGISTRY.histogram(
    "solver_rpc_phase_duration_seconds",
    "One sidecar RPC split by phase (encode|transit|kernel|decode): encode/"
    "decode are the client codec, kernel is the sidecar's reported solve "
    "time, transit is wire+HTTP overhead (total - kernel)",
)
SOLVER_RPC_FAILURES = REGISTRY.counter(
    "solver_rpc_failures_total",
    "Sidecar RPCs abandoned after retries, by cause "
    "(timeout|error|circuit_open|injected|decode|shed — shed is the"
    " gateway's 429 admission rejection, degraded without retries once"
    " Retry-After exceeds the solve budget)",
)
SOLVER_RPC_RETRIES = REGISTRY.counter(
    "solver_rpc_retries_total",
    "Individual sidecar RPC attempts that failed and were retried",
)
SOLVER_RPC_FALLBACKS = REGISTRY.counter(
    "solver_rpc_fallbacks_total",
    "Solves degraded to the host-greedy path because the sidecar was "
    "unavailable, by endpoint (solve|consolidate)",
)
SOLVER_CIRCUIT_STATE = REGISTRY.gauge(
    "solver_circuit_breaker_state",
    "Sidecar circuit breaker: 0 closed, 1 half-open, 2 open — labeled by"
    " tenant so fleet dashboards see WHICH operators are degraded to"
    " greedy, not just that someone is",
)
SOLVERD_SCHED_CACHE = REGISTRY.counter(
    "solverd_scheduler_cache_total",
    "Sidecar DeviceScheduler reuse across RPC solves by outcome (hit|miss)"
    " — a hit carries the prepared-state caches across the wire boundary",
)
SOLVERD_RESTARTS = REGISTRY.counter(
    "solverd_restarts_total",
    "Sidecar processes respawned by the supervisor, by cause: crash (the"
    " child died or was watchdog-killed; charges crash-loop backoff) vs"
    " drain (a clean drain-exit — the child flushed its queue and asked to"
    " be restarted; respawns immediately, never charges backoff)",
)
SOLVERD_RESPAWN_STORM = REGISTRY.gauge(
    "solverd_respawn_storm",
    "1 while a supervised sidecar member exceeded the respawn-storm"
    " threshold inside the sliding window (member-labeled): crash-only"
    " churn is routine and rides solverd_restarts_total, but a member"
    " respawning this often is MELTING — readyz degrades while the storm"
    " holds so probes and the digital twin can tell the two apart",
)
SOLVER_RESULT_REJECTED = REGISTRY.counter(
    "solver_result_rejected_total",
    "Solve results that failed host-side verification (solver/verify.py),"
    " by violated-invariant reason and solve path (inproc|sidecar|frontier);"
    " every rejection degrades that solve to the greedy path — a moving"
    " counter means the device tier is producing untrustworthy packings",
)
SOLVER_PREEMPTION_EVICTIONS = REGISTRY.counter(
    "solver_preemption_evictions_total",
    "Bound pods evicted to admit strictly-higher-tier pending pods"
    " (gangsched eviction claims executed by the operator as"
    " drain-before-bind) — each eviction was verified legal (victim"
    " strictly lower tier than a pod its freed capacity admitted)",
)
SOLVER_GANG_UNSCHEDULABLE = REGISTRY.counter(
    "solver_gang_unschedulable_total",
    "Pod groups reported whole-gang unschedulable (placed count below the"
    " gang's min-count → the kernel rolled the partial placement back, or"
    " the host backstop stripped it) — atomicity holding, not failing;"
    " partial materialization is a VERIFIER rejection, never a counter",
)
SOLVER_QUARANTINE_ENTRIES = REGISTRY.gauge(
    "solverd_quarantine_entries",
    "Problem fingerprints currently quarantined as poison pills, by site"
    " (client: the operator routes them straight to greedy; gateway: the"
    " sidecar refuses them pre-decode with 422)",
)
SOLVER_QUARANTINE_ROUTED = REGISTRY.counter(
    "solver_quarantine_routed_total",
    "Requests short-circuited by an active poison-pill quarantine entry,"
    " by site — device grants and sidecar respawns this problem did NOT"
    " burn",
)
SOLVERD_WATCHDOG_TRIPS = REGISTRY.counter(
    "solverd_watchdog_trips_total",
    "Device-step watchdog trips: the exclusive device phase exceeded its"
    " hard wall-clock bound and the sidecar exited crash-only (queued"
    " requests were flushed with 503 first; the supervisor respawns)",
)

# -- fleetd: the multi-tenant solve gateway (solver/fleet.py) --------------

SOLVERD_QUEUE_DEPTH = REGISTRY.gauge(
    "solverd_admission_queue_depth",
    "Requests admitted and not yet finished (queued + host phase + on"
    " device); at the configured bound the gateway sheds with 429 and"
    " /healthz flips ready:false (overloaded, NOT dead)",
)
SOLVERD_QUEUE_WAIT = REGISTRY.histogram(
    "solverd_queue_wait_seconds",
    "Per-request wait from host-phase ready to device grant, by tenant —"
    " the cross-tenant contention signal the fair queue bounds",
)
SOLVERD_SHED = REGISTRY.counter(
    "solverd_admission_shed_total",
    "Requests rejected by admission control, by tenant and reason"
    " (capacity|deadline|expired); every shed degrades that solve to the"
    " client's host greedy path, never to a stall",
)
SOLVERD_TENANT_SOLVES = REGISTRY.counter(
    "solverd_tenant_solves_total",
    "Requests served to completion, by tenant and endpoint"
    " (solve|consolidate) — the fleet's per-operator traffic ledger",
)
SOLVERD_SCHED_CACHE_EVICTIONS = REGISTRY.counter(
    "solverd_scheduler_cache_evictions_total",
    "DeviceScheduler cache entries dropped at the LRU bound, by reason"
    " (entries|bytes) — sustained evictions mean the fleet's problem mix"
    " outgrew the cache budget (expect re-prepare cost on every solve)",
)
SOLVERD_SCHED_CACHE_ENTRIES = REGISTRY.gauge(
    "solverd_scheduler_cache_entries",
    "DeviceScheduler cache entries currently resident",
)
SOLVERD_SCHED_CACHE_BYTES = REGISTRY.gauge(
    "solverd_scheduler_cache_bytes",
    "Approximate bytes pinned by cached DeviceSchedulers (encoded-request"
    " size proxy per entry, never exceeds the configured bound)",
)

# -- delta wire + fleet routing (solver/segments.py, solver/remote.py) -----

SOLVERD_SEGSTORE_ENTRIES = REGISTRY.gauge(
    "solverd_segment_store_entries",
    "Content-addressed solve-request segments resident in the sidecar's"
    " SegmentStore — the working set the delta wire elides from every"
    " manifest request",
)
SOLVERD_SEGSTORE_BYTES = REGISTRY.gauge(
    "solverd_segment_store_bytes",
    "Bytes pinned by resident segments (canonical JSON bytes per segment,"
    " never exceeds the configured bound)",
)
SOLVERD_SEGSTORE_EVICTIONS = REGISTRY.counter(
    "solverd_segment_store_evictions_total",
    "Segments dropped from the store, by reason (ttl|entries|bytes) —"
    " sustained entries/bytes evictions mean the fleet's snapshot mix"
    " outgrew the store budget (expect miss/re-upload rounds); ttl is"
    " routine idle expiry",
)
SOLVER_SEGMENT_WIRE_BYTES = REGISTRY.counter(
    "solver_segment_wire_bytes_total",
    "Solve-request bytes shipped to the sidecar, by payload kind:"
    " manifest = pure digest manifests (the steady-state delta wire),"
    " segment = manifests carrying segment uploads (cold start or a"
    " miss repair), full = whole-problem bodies (wire_mode=full or the"
    " manifest fallback) — the delta wire's headline ratio is"
    " (manifest+segment) vs full for the same traffic",
)
SOLVER_FLEET_ROUTED = REGISTRY.counter(
    "solver_fleet_routed_total",
    "Solve RPCs placed by the client-side fleet router, by reason:"
    " affinity = the rendezvous pick for the manifest's catalog digest"
    " (warm prepared-state caches keep hitting), spill = least-loaded"
    " placement (an answered refusal — shed/drain/quarantine — re-routed,"
    " or affinity disabled), degraded = the affinity pick's breaker was"
    " open so the next-best healthy member served",
)

# -- elastic tier + brownout ladder (solver/autoscale.py, ISSUE 17) --------

SOLVER_FLEET_SIZE = REGISTRY.gauge(
    "solver_fleet_size",
    "Live solverd fleet members after the autoscaler's last action — the"
    " tier-$ surface the ledger charges member-seconds against",
)
SOLVER_FLEET_SCALE = REGISTRY.counter(
    "solver_fleet_scale_total",
    "Autoscaler actions taken, by direction: up = a member spawned"
    " (FleetSupervisor.add_member), down = the least-loaded member"
    " retired through the faultless drain path (retire_member),"
    " rung_up/rung_down = a brownout ladder transition pushed to the"
    " fleet at max scale",
)
SOLVERD_BROWNOUT_RUNG = REGISTRY.gauge(
    "solverd_brownout_rung",
    "This daemon's brownout ladder rung (0 = clear, 1 = relax served as"
    " FFD, 2 = + widened batch window, 3 = + halved admission capacity)"
    " — an explicit degradation STATE, never a verification change",
)
SOLVERD_BROWNOUT_SERVED = REGISTRY.counter(
    "solverd_brownout_served_total",
    "Relax-mode requests rewritten to FFD by a held brownout rung, by"
    " rung — the anytime answers the ladder's cheapest rung bought"
    " instead of sheds",
)

# -- incremental re-solve (solver/incremental.py, ISSUE 16) ----------------

SOLVER_INCREMENTAL = REGISTRY.counter(
    "solver_incremental_total",
    "Solves that entered the incremental engine, by outcome: warm = the"
    " whole prior packing replayed (zero diff), partial = clean classes"
    " pinned + dirty pods sub-solved, full = fresh solve (ledger miss /"
    " amnesia, core change, topology/gang structure, or a dirty set past"
    " the proportionality bound), drift_reset = the drift controller"
    " forced the full solve (interval or node-count regression),"
    " rejected = a replayed packing failed the self-check verifier and"
    " degraded to a fresh solve (deliberately NOT counted on"
    " solver_result_rejected_total — that counter is the client-facing"
    " corruption signal and stays unmoved by engine self-distrust)",
)
SOLVER_LEDGER_ENTRIES = REGISTRY.gauge(
    "solver_packing_ledger_entries",
    "Prior-solve packings resident in the PackingLedger — the warm-start"
    " working set keyed by mode-suffixed problem fingerprint",
)
SOLVER_LEDGER_BYTES = REGISTRY.gauge(
    "solver_packing_ledger_bytes",
    "Approximate bytes pinned by resident ledger entries (uid/name"
    " reference accounting, never exceeds the configured bound)",
)

# -- continuous cross-tenant solve batching (solver/fleet.py coalescer) ----

SOLVERD_BATCH_SIZE = REGISTRY.histogram(
    "solverd_batch_size",
    "Problems per exclusive device grant: 1 = a solo grant, >1 = the"
    " coalescer dispatched N compatible queued problems as one vmapped"
    " device batch — the continuous-batching amortization signal",
)
SOLVERD_BATCH_COALESCED = REGISTRY.counter(
    "solverd_batch_coalesced_total",
    "Problems that rode another problem's device grant instead of waiting"
    " for their own (batch members beyond the leader) — each one is a"
    " whole device window the fleet did not serialize",
)
SOLVERD_BATCH_WINDOW_WAIT = REGISTRY.histogram(
    "solverd_batch_window_wait_seconds",
    "Time the grant leader held the device idle inside the batching"
    " window waiting for decoding requests to reach the queue — the"
    " bounded latency cost of coalescing (--batch-window-ms, 0 = off)",
)
SOLVERD_BATCH_PADDING = REGISTRY.histogram(
    "solverd_batch_padding_ratio",
    "Fraction of the padded problem axis occupied by inert pad rows per"
    " vmapped dispatch (the batch axis pads to a power of two to bound"
    " jit-cache growth) — sustained high ratios mean the max batch size"
    " or the traffic shape wastes device work on padding",
)
