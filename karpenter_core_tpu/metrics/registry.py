"""Prometheus-style metrics registry (reference: pkg/metrics/{metrics,
constants,store}.go — namespace `karpenter`, duration buckets, Measure()).

Self-contained: metrics accumulate in-process and render in the Prometheus
text exposition format; an HTTP scrape endpoint is a thin wrapper away and
out of scope for the framework core."""
from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

NAMESPACE = "karpenter"

# pkg/metrics/constants.go DurationBuckets
DURATION_BUCKETS = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10,
    20, 30, 45, 60, 120, 180, 300, 450, 600,
]


def _labelkey(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self.values: Dict[tuple, float] = {}

    def inc(self, labels: Optional[Dict[str, str]] = None, by: float = 1.0):
        k = _labelkey(labels or {})
        self.values[k] = self.values.get(k, 0.0) + by

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self.values.get(_labelkey(labels or {}), 0.0)


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self.values: Dict[tuple, float] = {}

    def set(self, value: float, labels: Optional[Dict[str, str]] = None):
        self.values[_labelkey(labels or {})] = value

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self.values.get(_labelkey(labels or {}), 0.0)

    def reset(self):
        self.values = {}


class Histogram:
    def __init__(self, name: str, help_: str = "", buckets=None):
        self.name = name
        self.help = help_
        self.buckets = list(buckets or DURATION_BUCKETS)
        self.counts: Dict[tuple, List[int]] = {}
        self.sums: Dict[tuple, float] = {}
        self.totals: Dict[tuple, int] = {}

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None):
        k = _labelkey(labels or {})
        counts = self.counts.setdefault(k, [0] * len(self.buckets))
        for i, b in enumerate(self.buckets):
            if value <= b:
                counts[i] += 1
        self.sums[k] = self.sums.get(k, 0.0) + value
        self.totals[k] = self.totals.get(k, 0) + 1

    def percentile(self, q: float, labels: Optional[Dict[str, str]] = None) -> float:
        """Approximate quantile from bucket counts."""
        k = _labelkey(labels or {})
        total = self.totals.get(k, 0)
        if not total:
            return 0.0
        target = q * total
        for i, b in enumerate(self.buckets):
            if self.counts[k][i] >= target:
                return b
        return float("inf")

    @contextmanager
    def time(self, labels: Optional[Dict[str, str]] = None):
        """metrics.Measure() (constants.go:58-63)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0, labels)


class Registry:
    def __init__(self):
        self.metrics: Dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help_))

    def histogram(self, name: str, help_: str = "", buckets=None) -> Histogram:
        return self._get_or_make(name, lambda: Histogram(name, help_, buckets))

    def _get_or_make(self, name, factory):
        m = self.metrics.get(name)
        if m is None:
            m = factory()
            self.metrics[name] = m
        return m

    def render(self) -> str:
        """Prometheus text exposition format. Iterates over list() snapshots
        so a scrape from the health server's handler thread survives the
        operator thread registering metrics/series mid-render (single torn
        values are acceptable scrape noise; a 'dict changed size' crash is
        not)."""
        lines = []
        for name, m in sorted(list(self.metrics.items())):
            full = f"{NAMESPACE}_{name}"
            if m.help:
                lines.append(f"# HELP {full} {m.help}")
            if isinstance(m, (Counter, Gauge)):
                kind = "counter" if isinstance(m, Counter) else "gauge"
                lines.append(f"# TYPE {full} {kind}")
                for k, v in sorted(list(m.values.items())):
                    lines.append(f"{full}{_fmt_labels(k)} {v:g}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {full} histogram")
                for k in sorted(list(m.totals)):
                    cum = 0
                    for i, b in enumerate(m.buckets):
                        cum = m.counts[k][i]
                        lines.append(
                            f"{full}_bucket{_fmt_labels(k, le=b)} {cum}"
                        )
                    lines.append(
                        f"{full}_bucket{_fmt_labels(k, le='+Inf')} {m.totals[k]}"
                    )
                    lines.append(f"{full}_sum{_fmt_labels(k)} {m.sums[k]:g}")
                    lines.append(f"{full}_count{_fmt_labels(k)} {m.totals[k]}")
        return "\n".join(lines) + "\n"


def _fmt_labels(key: tuple, le=None) -> str:
    parts = [f'{k}="{v}"' for k, v in key]
    if le is not None:
        parts.append(f'le="{le}"')
    return "{" + ",".join(parts) + "}" if parts else ""


REGISTRY = Registry()
